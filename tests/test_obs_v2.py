"""Obs v2 tests (ISSUE PR 15 acceptance list): continuous time-series
telemetry (aggregation ring, JSONL flushes, Prometheus text, rapidstop),
exact critical-path attribution (serial and under serve concurrency,
with shuffle + spill + retry in the window), the cross-run regression
sentinel (fires on an injected slowdown, silent on clean runs, offline
via rapidshist --regressions), per-site ring-drop accounting with the
truncation banner, and session-stamped event-log round-trips."""

import json
import os
import subprocess
import sys
import time

import pytest

from compare import tpu_session
from spark_rapids_tpu import functions as F
from spark_rapids_tpu.fault import inject
from spark_rapids_tpu.history import store
from spark_rapids_tpu.history.fragcache import fragment_cache
from spark_rapids_tpu.obs import critpath as obs_critpath
from spark_rapids_tpu.obs import export as obs_export
from spark_rapids_tpu.obs import sentinel
from spark_rapids_tpu.obs import timeseries as obs_ts
from spark_rapids_tpu.obs.timeseries import TelemetryRing
from spark_rapids_tpu.serve import ServeScheduler

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    """Process-global state (fault registry, sentinel totals, store
    cache, fragment cache, telemetry ring) must never leak across
    tests."""
    saved_ring = obs_ts._RING
    sentinel.reset_alerts_total()
    store.invalidate_cache()
    fragment_cache().clear()
    yield
    inject.uninstall()
    sentinel.reset_alerts_total()
    store.invalidate_cache()
    fragment_cache().clear()
    obs_ts._RING = saved_ring


def _df(s, n=600, seed=0):
    return s.create_dataframe(
        {"k": [(seed + i) % 7 for i in range(n)],
         "v": [(seed + 3 * i) % 997 for i in range(n)]},
        num_partitions=2)


# -- telemetry ring units -----------------------------------------------------


def test_ring_rotation_and_drop_oldest():
    r = TelemetryRing(interval_ms=1, max_intervals=2)
    for _ in range(4):
        r.record_span("dispatch", 10_000, 64)
        time.sleep(0.003)  # force the next record into a newer bucket
    r.record_span("dispatch", 10_000, 64)
    done = r.snapshot()
    assert len(done) <= 2  # bounded
    assert r.completed_total >= 3
    assert r.dropped_intervals >= 1  # drop-OLDEST counted
    # the ring keeps the NEWEST intervals: indices strictly increase
    idxs = [iv.idx for iv in done]
    assert idxs == sorted(idxs)


def test_ring_value_samples_bounded_per_interval():
    r = TelemetryRing(interval_ms=60_000, max_intervals=4)
    for i in range(obs_ts.MAX_VALUES_PER_INTERVAL + 88):
        r.record_value("serve.latency_ms", float(i))
    vals = r.window_values("serve.latency_ms")
    assert len(vals) == obs_ts.MAX_VALUES_PER_INTERVAL
    assert vals[0] == 0.0  # first samples win (bounded append)


def test_failing_gauge_never_breaks_export():
    r = TelemetryRing(interval_ms=1000, max_intervals=4)

    def bad():
        raise RuntimeError("torn-down subsystem")

    r.register_gauge("bad", bad)
    r.register_gauge("good", lambda: 7.0)
    g = r.sample_gauges()
    assert g["good"] == 7.0
    assert "bad" not in g
    assert "telemetry.dropped_intervals" in g
    # and the Prometheus text still renders with the bad gauge armed
    assert "rapids_good 7" in r.prometheus_text()


def test_flush_jsonl_is_incremental(tmp_path):
    r = TelemetryRing(interval_ms=1, max_intervals=128)
    path = str(tmp_path / "telemetry.jsonl")
    r.record_span("dispatch", 5_000, 0)
    time.sleep(0.003)
    n1 = r.flush_jsonl(path)  # roll_now closes the stale interval
    assert n1 >= 1
    assert r.flush_jsonl(path) == 0  # nothing new -> nothing written
    r.record_span("h2d", 7_000, 1 << 20)
    time.sleep(0.003)
    n2 = r.flush_jsonl(path)
    assert n2 >= 1
    intervals = obs_ts.read_telemetry_log(path)
    assert len(intervals) == n1 + n2  # appended, never rewritten
    sites = {s for iv in intervals for s in (iv.get("sites") or {})}
    assert {"dispatch", "h2d"} <= sites
    # the newest flushed interval carries the gauge samples
    assert "telemetry.dropped_intervals" in (intervals[-1].get("gauges")
                                             or {})


def test_configure_keeps_ring_when_shape_unchanged():
    obs_ts.configure(True, 77, 9)
    r1 = obs_ts.ring()
    assert r1 is not None and r1.interval_ns == 77 * 1_000_000
    obs_ts.configure(True, 77, 9)
    assert obs_ts.ring() is r1  # repeat execute never resets the ring
    obs_ts.configure(True, 78, 9)
    assert obs_ts.ring() is not r1  # shape change replaces it
    obs_ts.configure(False, 78, 9)
    assert obs_ts.ring() is None
    obs_ts.record_span("dispatch", 1, 0)  # disabled fold is a no-op
    assert obs_ts.completed_total() == 0


def test_prometheus_text_parses():
    r = TelemetryRing(interval_ms=1, max_intervals=8)
    r.record_span("dispatch", 123_000, 4096)
    time.sleep(0.003)
    r.register_gauge("catalog.device_bytes", lambda: 1024.0)
    text = r.prometheus_text()
    assert text.endswith("\n")
    for line in text.strip().splitlines():
        if line.startswith("#"):
            parts = line.split()
            assert parts[:2] == ["#", "TYPE"] and parts[3] in (
                "counter", "gauge"), line
            continue
        name, val = line.rsplit(" ", 1)
        float(val)  # every sample value is numeric
        assert name.split("{")[0].startswith("rapids_")
    assert 'rapids_site_events_total{site="dispatch"} 1' in text
    assert "rapids_catalog_device_bytes 1024" in text


def test_render_intervals_empty_and_window():
    assert obs_ts.render_intervals([]) == "(no telemetry intervals)"
    ivs = [{"type": "interval", "idx": i, "t0_ns": i * 10, "dur_ns": 10,
            "sites": {"dispatch": [1, 5_000_000, 0]}} for i in range(3)]
    out = obs_ts.render_intervals(ivs, last=2)
    assert "2 interval(s)" in out
    assert "window (2 intervals)" in out
    assert "dispatch" in out


# -- critical path: unit ------------------------------------------------------


def test_critpath_exact_partition_with_overlap_and_priority():
    # window [1000, 1100): exchange covers [1005,1040) with a device
    # span nested inside [1010,1030) — device outranks exchange, so the
    # exchange is credited only its host-side remainder.
    evs = [
        {"kind": "span", "site": "exchange", "t0": 1005, "t1": 1040},
        {"kind": "span", "site": "device", "t0": 1010, "t1": 1030},
        {"kind": "span", "site": "io", "t0": 1050, "t1": 1060},
        {"kind": "instant", "site": "fault", "t0": 1055, "t1": 1055},
        {"kind": "span", "site": "h2d", "t0": 1090, "t1": 1500},  # clipped
        {"kind": "span", "site": "spill", "t0": 0, "t1": 50},  # unstamped
    ]
    cp = obs_critpath.compute(evs, 1000, 1100)
    assert cp.total_ns == 100
    assert sum(cp.segments.values()) == cp.total_ns  # exact by construction
    assert cp.segments == {"exchange": 15, "device": 20, "io": 10,
                           "h2d": 10, "wait": 45}
    assert cp.attributed_ns == 55
    # the chain is a merged, ordered partition of the window
    assert cp.chain[0] == ("wait", 1000, 1005)
    assert [c[0] for c in cp.chain] == ["wait", "exchange", "device",
                                        "exchange", "wait", "io", "wait",
                                        "h2d"]
    assert all(a[2] == b[1] for a, b in zip(cp.chain, cp.chain[1:]))
    assert cp.top_site() == "wait"
    assert "critical path: " in cp.summary()


def test_critpath_empty_window_and_unknown_site():
    assert obs_critpath.compute([], 50, 50).segments == {}
    cp = obs_critpath.compute(
        [{"kind": "span", "site": "weird", "t0": 10, "t1": 20},
         {"kind": "span", "site": "device", "t0": 12, "t1": 14}], 10, 20)
    assert cp.segments == {"weird": 8, "device": 2}  # unknown = lowest rank


# -- critical path: end to end ------------------------------------------------


def _assert_exact(p):
    cp = obs_critpath.from_profile(p)
    assert cp is not None
    assert cp.total_ns == p.qt1_ns - p.qt0_ns
    assert sum(cp.segments.values()) == cp.total_ns, cp.segments
    return cp


def test_critpath_exact_on_shuffle_spill_retry_query():
    """The pinned exactness query: a shuffled hash join that spills
    (tiny device budget) and retries (dispatch:oom@2) — every
    nanosecond of the query window is attributed, metric included."""
    from spark_rapids_tpu.runtime.device import DeviceRuntime
    DeviceRuntime.reset()
    try:
        s = tpu_session(**{
            "spark.rapids.sql.tpu.faults.spec": "dispatch:oom@2",
            "spark.rapids.sql.tpu.exchange.collapseLocal": False,
            "spark.sql.autoBroadcastJoinThreshold": -1,
            "spark.rapids.memory.tpu.spillBudgetBytes": 64 * 1024,
            "spark.rapids.sql.tpu.spill.async.enabled": False,
        })
        n = 8192
        left = s.create_dataframe(
            {"k": [i % 500 for i in range(n)],
             "v": [(3 * i) % 997 for i in range(n)]}, num_partitions=3)
        right = s.create_dataframe(
            {"k": list(range(500)), "w": list(range(500))},
            num_partitions=2)
        s.execute(left.join(right, on="k", how="inner").plan)
        m = s.last_metrics
        p = s.query_history()[-1]
        cp = _assert_exact(p)
        assert m["critpathAttributedNs"] == cp.attributed_ns
        assert 0 < cp.attributed_ns <= cp.total_ns
        # the decomposition saw the shuffle, the spill and the retry
        sites = {ev.site for ev in p.events}
        assert "retry" in sites or "fault" in sites
        assert "exchange" in sites
        assert "spill" in sites
        assert cp.top_site() != ""
    finally:
        DeviceRuntime.reset()


def test_critpath_exact_under_serve_concurrency():
    """3-thread serve: each query's window still decomposes exactly —
    spans from helper threads (decode pool, spill writer) land in the
    right query's profile and never break the partition."""
    s = tpu_session()
    before = len(s.query_history())
    dfs = [_df(s, seed=7 * i).group_by("k").sum("v") for i in range(6)]
    with ServeScheduler(s, max_concurrency=3) as sched:
        futs = [sched.submit(df) for df in dfs]
        for f in futs:
            f.result(timeout=120)
    hist = s.query_history()[before:]
    assert len(hist) == 6
    for p in hist:
        cp = _assert_exact(p)
        assert 0 <= cp.segments.get("wait", 0) <= cp.total_ns


# -- regression sentinel ------------------------------------------------------


def test_sentinel_check_band_math():
    agg = {"n": 5, "keys": {"wall_ns": {"median": 100e6, "mad": 1e6}}}
    # band = median + threshold * max(MAD, 25% median, 2ms floor)
    #      = 100e6 + 4 * 25e6 = 200e6
    assert sentinel.check({"wall_ns": 200e6}, agg, 4.0, 3) == []
    alerts = sentinel.check({"wall_ns": 200e6 + 1}, agg, 4.0, 3)
    assert [a["key"] for a in alerts] == ["wall_ns"]
    assert alerts[0]["band"] == 200e6
    assert alerts[0]["runs"] == 5
    assert sentinel.alerts_total() == 1
    # thin baseline: never alert below min_runs
    assert sentinel.check({"wall_ns": 1e12}, dict(agg, n=2), 4.0, 3) == []
    # downward excursions are not regressions
    assert sentinel.check({"wall_ns": 1.0}, agg, 4.0, 3) == []
    # unguarded keys are ignored
    agg2 = {"n": 5, "keys": {"out_rows": {"median": 1.0, "mad": 0.0}}}
    assert sentinel.check({"out_rows": 1e9}, agg2, 4.0, 3) == []


def _hist_session(hist_dir, **confs):
    # fragments off so warm repeats re-execute (0-dispatch fragment
    # serves would dodge the injected fault); seeding off so every run
    # keeps the identical unseeded plan fingerprint; faults.spec preset
    # empty so toggling it restores this exact conf state and a clean
    # repeat reuses the cached plan instead of recompiling
    return tpu_session(**{
        "spark.rapids.sql.tpu.history.dir": str(hist_dir),
        "spark.rapids.sql.tpu.history.fragments.enabled": False,
        "spark.rapids.sql.tpu.history.seed.enabled": False,
        "spark.rapids.sql.tpu.faults.spec": "",
        **confs})


def test_sentinel_fires_on_injected_slowdown(tmp_path):
    """4 clean runs build the baseline (the 4th, compared against the
    first 3, stays silent); a dispatch:slow run then alerts, emits the
    'regression' obs instant, and rapidshist --regressions finds the
    same alert offline with exit code 1."""
    hist = tmp_path / "h"
    s = _hist_session(hist)
    df = _df(s).filter(F.col("v") > 10)
    for _ in range(4):
        s.execute(df.plan)
        assert s.last_metrics["regressionAlerts"] == 0, s.last_metrics

    # same session, same plan fingerprint; the faults. conf namespace is
    # excluded from the conf signature, so the slow run is compared
    # against the clean baseline it just built
    s.conf.set("spark.rapids.sql.tpu.faults.spec",
               "dispatch:slow=500ms@1+")
    s.execute(df.plan)
    m = s.last_metrics
    assert m["regressionAlerts"] >= 1, m
    assert m["faultsInjected"] >= 1, m
    assert sentinel.alerts_total() >= 1
    p = s.query_history()[-1]
    regs = [ev for ev in p.events
            if ev.site == "history" and ev.name == "regression"]
    assert len(regs) == m["regressionAlerts"]
    assert any((ev.payload or {}).get("key") == "wall_ns" for ev in regs)

    # offline: the store's newest run (the slow one) vs the runs before
    # it — same alert, exit code 1
    tool = os.path.join(REPO_ROOT, "tools", "rapidshist.py")
    proc = subprocess.run(
        [sys.executable, tool, str(hist), "--regressions"],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "REGRESSION" in proc.stdout
    assert "wall_ns" in proc.stdout

    # a clean repeat against the now-5-run baseline stays silent (the
    # slow outlier cannot drag the median out of the clean band), and
    # restoring the preset conf state reuses the cached plan
    s.conf.set("spark.rapids.sql.tpu.faults.spec", "")
    s.execute(df.plan)
    assert s.last_metrics["regressionAlerts"] == 0, s.last_metrics
    assert s.last_metrics["compileCount"] == 0, s.last_metrics


def test_sentinel_silent_on_clean_runs_and_disable(tmp_path):
    hist = tmp_path / "h"
    s = _hist_session(hist)
    df = _df(s).filter(F.col("v") > 10)
    for _ in range(5):
        s.execute(df.plan)
        assert s.last_metrics["regressionAlerts"] == 0, s.last_metrics
    tool = os.path.join(REPO_ROOT, "tools", "rapidshist.py")
    proc = subprocess.run(
        [sys.executable, tool, str(hist), "--regressions"],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no regressions" in proc.stdout
    # sentinel.enabled=false skips the comparison entirely, even with a
    # real slowdown injected against a mature baseline
    s.conf.set("spark.rapids.sql.tpu.sentinel.enabled", False)
    s.conf.set("spark.rapids.sql.tpu.faults.spec",
               "dispatch:slow=500ms@1+")
    s.execute(df.plan)
    assert s.last_metrics["faultsInjected"] >= 1
    assert s.last_metrics["regressionAlerts"] == 0


# -- ring drops: per-site accounting + truncation banner ----------------------


def test_truncated_profile_names_dropped_sites():
    s = tpu_session(**{"spark.rapids.sql.tpu.obs.ring.maxEvents": 4})
    _df(s).group_by("k").sum("v").collect()
    p = s.query_history()[-1]
    assert p.dropped > 0
    assert sum(p.dropped_by_site.values()) == p.dropped
    banner = p.summary()
    assert "TRUNCATED" in banner
    assert "obs.ring.maxEvents" in banner
    top_site = max(p.dropped_by_site.items(), key=lambda kv: kv[1])[0]
    assert top_site in banner
    # an untruncated profile shows no banner
    s2 = tpu_session()
    _df(s2).group_by("k").sum("v").collect()
    assert "TRUNCATED" not in s2.query_history()[-1].summary()


# -- serve sliding-window percentiles -----------------------------------------


def test_serve_stats_window_percentiles():
    s = tpu_session()
    dfs = [_df(s, seed=3 * i).group_by("k").sum("v") for i in range(5)]
    with ServeScheduler(s, max_concurrency=2) as sched:
        futs = [sched.submit(df, tenant="t") for df in dfs]
        for f in futs:
            f.result(timeout=120)
        st = sched.stats()
    assert st["completed"] == 5
    assert st["window_seconds"] > 0
    assert 0 < st["window_p50_ms"] <= st["window_p99_ms"]
    tn = st["tenants"]["t"]
    assert 0 < tn["window_p50_ms"] <= tn["window_p99_ms"]
    # all-time percentile fields are still reported alongside
    assert tn["p50_ms"] > 0


# -- event log: session stamps + rapidstop ------------------------------------


def test_event_log_roundtrips_session_and_window(tmp_path):
    log_dir = str(tmp_path / "obslog")
    s1 = tpu_session(**{"spark.rapids.sql.tpu.obs.eventLogDir": log_dir})
    _df(s1).group_by("k").sum("v").collect()
    s2 = tpu_session(**{"spark.rapids.sql.tpu.obs.eventLogDir": log_dir})
    _df(s2).filter(F.col("v") > 10).collect()
    log = os.path.join(log_dir, [f for f in os.listdir(log_dir)
                                 if f.startswith("events-")][0])
    queries = obs_export.read_event_log(log)
    assert len(queries) == 2
    sessions = {q["session"] for q in queries}
    assert len(sessions) == 2  # distinct session ids round-trip
    for q in queries:
        assert 0 < q["t0_ns"] < q["t1_ns"]
        assert isinstance(q["dropped_by_site"], dict)

    # rapidsprof groups by session and reconstructs the exact critpath
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "rapidsprof.py"),
         log, "--critpath"],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.count("critical path:") == 2
    assert "== session" in proc.stdout
    assert "| sess |" in proc.stdout


def test_rapidstop_renders_flushed_telemetry_without_jax(tmp_path):
    log_dir = str(tmp_path / "obslog")
    s = tpu_session(**{
        "spark.rapids.sql.tpu.obs.eventLogDir": log_dir,
        "spark.rapids.sql.tpu.obs.telemetry.intervalMs": 25,
    })
    df = _df(s, n=4096).group_by("k").sum("v")
    s.execute(df.plan)
    time.sleep(0.06)  # let the open interval's window pass
    s.execute(df.plan)  # second execute flushes the completed intervals
    assert s.last_metrics["telemetryIntervals"] >= 1
    tpath = os.path.join(log_dir, f"telemetry-{os.getpid()}.jsonl")
    assert os.path.exists(tpath)
    intervals = obs_ts.read_telemetry_log(tpath)
    assert intervals
    assert any("dispatch" in (iv.get("sites") or {}) for iv in intervals)

    # the CLI renders the table and the Prometheus view in a fresh
    # process that must never import jax (runtime-free discipline)
    tool = os.path.join(REPO_ROOT, "tools", "rapidstop.py")
    driver = (
        "import runpy, sys\n"
        "tool, path = sys.argv[1], sys.argv[2]\n"
        "sys.argv = [tool, path, '--once']\n"
        "try:\n"
        "    runpy.run_path(tool, run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    assert not e.code, e.code\n"
        "assert 'jax' not in sys.modules, 'rapidstop imported jax'\n")
    proc = subprocess.run([sys.executable, "-c", driver, tool, tpath],
                          capture_output=True, text=True, cwd=REPO_ROOT,
                          timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "telemetry:" in proc.stdout
    assert "dispatch" in proc.stdout
    prom = subprocess.run([sys.executable, tool, tpath, "--prom"],
                          capture_output=True, text=True, cwd=REPO_ROOT,
                          timeout=120)
    assert prom.returncode == 0, prom.stderr
    assert "rapids_telemetry_intervals_total" in prom.stdout
    assert 'rapids_site_events_total{site="dispatch"}' in prom.stdout
    missing = subprocess.run(
        [sys.executable, tool, str(tmp_path / "nope.jsonl"), "--once"],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert missing.returncode == 2
    assert "(no telemetry intervals)" in missing.stdout


def test_telemetry_disabled_records_nothing():
    s = tpu_session(**{
        "spark.rapids.sql.tpu.obs.telemetry.enabled": False})
    _df(s).group_by("k").sum("v").collect()
    assert s.last_metrics["telemetryIntervals"] == 0
    assert obs_ts.ring() is None
