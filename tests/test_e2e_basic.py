"""End-to-end DataFrame tests: TPU plan vs CPU plan results
(the HashAggregatesSuite / joins / sort / limit suites' pattern)."""

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.dataframe import Column
from spark_rapids_tpu.exprs.aggregates import (
    Average, Count, Max, Min, Sum, count_star,
)
from spark_rapids_tpu.exprs.base import Alias, ColumnRef

from compare import assert_tpu_cpu_equal, tpu_session

DATA = {
    "a": (T.INT, [1, 2, 2, 3, None, 5, 5, 5, 0, -7]),
    "b": (T.LONG, [10, 20, None, 40, 50, 60, 70, None, 90, 100]),
    "f": (T.DOUBLE, [0.5, None, 2.5, -3.5, 4.5, 5.5, float("nan"), 7.5,
                     8.5, -0.0]),
    "s": (T.STRING, ["apple", "bee", None, "cat", "dog", "bee", "eel",
                     "fox", "", "gnu"]),
}


def make_df(s, data=None, parts=3):
    return s.create_dataframe(data or DATA, num_partitions=parts)


def test_select_project_arith():
    assert_tpu_cpu_equal(
        lambda s: make_df(s).select(
            "a",
            (Column(ColumnRef("a")) + 1).alias("a1"),
            (Column(ColumnRef("b")) * 2).alias("b2"),
            (Column(ColumnRef("f")) / 2.0).alias("fh"),
        ), approx=True)


def test_filter():
    assert_tpu_cpu_equal(
        lambda s: make_df(s).filter(Column(ColumnRef("a")) > 1))


def test_filter_string_and_null():
    def q(s):
        df = make_df(s)
        return df.filter(df["s"].is_not_null() & (df["s"] != "bee"))
    assert_tpu_cpu_equal(q)


def test_groupby_agg():
    def q(s):
        df = make_df(s)
        return df.group_by("a").agg(
            Column(Alias(Sum(ColumnRef("b")), "sum_b")),
            Column(Alias(Count(ColumnRef("b")), "cnt_b")),
            Column(Alias(Min(ColumnRef("b")), "min_b")),
            Column(Alias(Max(ColumnRef("b")), "max_b")),
            Column(Alias(Average(ColumnRef("b")), "avg_b")),
        )
    assert_tpu_cpu_equal(q, approx=True)


def test_groupby_string_key():
    def q(s):
        df = make_df(s)
        return df.group_by("s").agg(
            Column(Alias(Count(ColumnRef("a")), "cnt")),
            Column(Alias(Sum(ColumnRef("a")), "sum_a")),
        )
    assert_tpu_cpu_equal(q)


def test_global_reduction():
    def q(s):
        df = make_df(s)
        return df.agg(Column(Alias(Sum(ColumnRef("b")), "sum_b")),
                      Column(Alias(count_star(), "n")))
    assert_tpu_cpu_equal(q)


def test_global_reduction_empty_input():
    def q(s):
        df = make_df(s)
        return df.filter(Column(ColumnRef("a")) > 1000).agg(
            Column(Alias(Sum(ColumnRef("b")), "sum_b")),
            Column(Alias(count_star(), "n")))
    assert_tpu_cpu_equal(q)


def test_orderby():
    def q(s):
        df = make_df(s)
        return df.order_by(df["a"].desc(), df["s"].asc())
    assert_tpu_cpu_equal(q, ignore_order=False)


def test_orderby_expression_key():
    def q(s):
        df = make_df(s)
        return df.order_by((df["a"] * -1).asc(), "b")
    assert_tpu_cpu_equal(q, ignore_order=False)


def test_limit():
    # limit is non-deterministic across partitions in general; use sorted
    def q(s):
        df = make_df(s)
        return df.order_by("b").limit(4)
    assert_tpu_cpu_equal(q, ignore_order=False)


def test_union():
    def q(s):
        df = make_df(s)
        return df.union(df)
    assert_tpu_cpu_equal(q)


def test_distinct():
    def q(s):
        df = make_df(s).select("a", "s")
        return df.distinct()
    assert_tpu_cpu_equal(q)


def test_join_inner():
    other = {
        "a": (T.INT, [2, 3, 5, 5, 8, None]),
        "v": (T.STRING, ["x", "y", "z", "w", "q", "n"]),
    }

    def q(s):
        df = make_df(s)
        d2 = s.create_dataframe(other, num_partitions=2)
        return df.join(d2, on="a", how="inner")
    assert_tpu_cpu_equal(q)


@pytest.mark.parametrize("bc", ["broadcast", "shuffle"])
@pytest.mark.parametrize("how", ["left", "right", "full", "left_semi",
                                 "left_anti"])
def test_join_types(how, bc):
    other = {
        "a": (T.INT, [2, 3, 5, 5, 8, None]),
        "v": (T.STRING, ["x", "y", "z", "w", "q", "n"]),
    }

    def q(s):
        df = make_df(s)
        d2 = s.create_dataframe(other, num_partitions=2)
        return df.join(d2, on="a", how=how)
    confs = {} if bc == "broadcast" else \
        {"spark.sql.autoBroadcastJoinThreshold": -1}
    assert_tpu_cpu_equal(q, confs=confs)


def test_broadcast_hint_forces_broadcast_plan():
    from spark_rapids_tpu import functions as F
    s = tpu_session()
    df = make_df(s)
    d2 = s.create_dataframe({"a": (T.INT, [1, 2]),
                             "w": (T.INT, [10, 20])})
    out = df.join(F.broadcast(d2), on="a", how="inner")
    out.collect()
    assert "TpuBroadcastHashJoin" in s.last_physical_plan.tree_string()


def test_join_multi_key_expr_cond():
    other = {
        "k": (T.INT, [1, 2, 2, 5]),
        "s2": (T.STRING, ["apple", "bee", "bee", "cat"]),
        "w": (T.LONG, [7, 8, 9, 10]),
    }

    def q(s):
        df = make_df(s)
        d2 = s.create_dataframe(other, num_partitions=2)
        return df.join(d2, on=(df["a"] == d2["k"]) & (df["s"] == d2["s2"]),
                       how="inner")
    assert_tpu_cpu_equal(q)


def test_cross_join():
    small = {"x": (T.INT, [1, 2])}

    def q(s):
        df = make_df(s).select("a")
        d2 = s.create_dataframe(small)
        return df.cross_join(d2)
    assert_tpu_cpu_equal(q)


def test_with_column_cast():
    def q(s):
        df = make_df(s)
        return df.with_column("al", df["a"].cast("bigint")) \
                 .with_column("fs", df["f"].cast("float"))
    assert_tpu_cpu_equal(q, approx=True)


def test_repartition_roundtrip():
    def q(s):
        df = make_df(s)
        return df.repartition(5, "a").select("a", "b")
    assert_tpu_cpu_equal(q)


def test_count_action():
    s = tpu_session()
    df = make_df(s)
    assert df.count() == 10


def test_string_functions():
    def q(s):
        df = make_df(s)
        return df.select(
            df["s"].substr(1, 2).alias("pre"),
            df["s"].contains("e").alias("has_e"),
            df["s"].startswith("b").alias("is_b"),
        )
    assert_tpu_cpu_equal(q)


def test_explain_and_fallback():
    # rand() has no deterministic TPU parity; just check explain shows TPU ops
    s = tpu_session()
    df = make_df(s).filter(Column(ColumnRef("a")) > 1).select("a")
    out = s.explain_plan(df.plan)
    assert "will run on TPU" in out


def test_enforce_tpu_mode():
    s = tpu_session(**{"spark.rapids.sql.test.enabled": True})
    df = make_df(s).filter(Column(ColumnRef("a")) > 1).select("a", "s")
    # should not raise: everything lands on TPU
    df.collect()


def test_large_batch_shrink_path():
    """Exercise shrink_to_fit + the sorted exchange split (big sparse
    batches; regression: shrink_to_fit import bug only hit at scale)."""
    import numpy as np
    from spark_rapids_tpu import functions as F
    n = 40_000
    rng = np.random.RandomState(1)
    data = {
        "k": (T.INT, rng.randint(0, 50, n)),
        "v": (T.LONG, rng.randint(0, 1000, n)),
        "s": (T.STRING, [f"s{int(x)}" for x in rng.randint(0, 50, n)]),
    }

    def q(s):
        df = s.create_dataframe(data, num_partitions=3)
        return df.filter(df["v"] < 40) \
                 .group_by("k", "s").agg(F.sum("v").alias("sv"),
                                         F.count("v").alias("cv"))
    assert_tpu_cpu_equal(q)


@pytest.mark.parametrize("bc", ["broadcast", "shuffle"])
@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "left_semi", "left_anti"])
def test_join_types_with_residual_condition(how, bc):
    """Residual conditions gate matches INSIDE the join for every type
    (GpuHashJoin.scala:265-271): a row whose matches all fail the
    condition must come out null-padded / kept / dropped per the type."""
    other = {
        "k": (T.INT, [2, 3, 5, 5, 8, None]),
        "w": (T.LONG, [15, 100, 55, 9, 70, 1]),
        "v": (T.STRING, ["x", "y", "z", "w", "q", "n"]),
    }

    def q(s):
        df = make_df(s)
        d2 = s.create_dataframe(other, num_partitions=2)
        return df.join(d2, on=(df["a"] == d2["k"]) & (df["b"] < d2["w"]),
                       how=how)
    confs = {} if bc == "broadcast" else \
        {"spark.sql.autoBroadcastJoinThreshold": -1}
    assert_tpu_cpu_equal(q, confs=confs)


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "left_semi", "left_anti"])
def test_nested_loop_join_types(how):
    """Non-equi-only conditions plan as a nested-loop join; all types run
    on TPU (GpuBroadcastNestedLoopJoinExec.scala:305 parity)."""
    other = {
        "k": (T.INT, [1, 3, 6, None]),
        "v": (T.STRING, ["p", "q", "r", "s"]),
    }

    def q(s):
        df = make_df(s).select("a", "s")
        d2 = s.create_dataframe(other)
        return df.join(d2, on=df["a"] < d2["k"], how=how)
    assert_tpu_cpu_equal(q)


def test_nested_loop_join_runs_on_tpu():
    s = tpu_session()
    df = make_df(s).select("a")
    d2 = s.create_dataframe({"k": (T.INT, [1, 3])})
    out = df.join(d2, on=df["a"] < d2["k"], how="left")
    out.collect()
    assert "TpuNestedLoopJoin(left)" in s.last_physical_plan.tree_string()


# ---------------------------------------------------------------------------
# Non-collapsed exchange matrix: collapseLocal=false exercises the device
# partition-split path (exchange.py device split + spillable outputs) that
# the mesh path builds on.
# ---------------------------------------------------------------------------

NO_COLLAPSE = {"spark.rapids.sql.tpu.exchange.collapseLocal": False}


@pytest.mark.parametrize("case", ["groupby", "groupby_str", "sort", "join",
                                  "window_less", "limit", "distinct"])
def test_non_collapsed_exchange_matrix(case):
    def q(s):
        df = make_df(s)
        if case == "groupby":
            return df.group_by("a").agg(
                Column(Alias(Sum(ColumnRef("b")), "sum_b")),
                Column(Alias(Count(ColumnRef("b")), "cnt")))
        if case == "groupby_str":
            return df.group_by("s").agg(
                Column(Alias(Sum(ColumnRef("a")), "sum_a")))
        if case == "sort":
            return df.order_by(df["a"].desc(), df["s"].asc())
        if case == "join":
            d2 = s.create_dataframe({
                "a": (T.INT, [2, 3, 5, None]),
                "w": (T.LONG, [1, 2, 3, 4])}, num_partitions=2)
            return df.join(d2, on="a", how="left")
        if case == "window_less":
            return df.select("a", "b").distinct()
        if case == "limit":
            return df.order_by("b").limit(4)
        return df.select("s").distinct()

    confs = dict(NO_COLLAPSE)
    if case == "join":
        confs["spark.sql.autoBroadcastJoinThreshold"] = -1
    assert_tpu_cpu_equal(q, confs=confs,
                         ignore_order=case not in ("sort", "limit"))


def test_metrics_surfaced():
    """session.last_metrics reports pipeline program counts, op metrics and
    catalog spill counters (GpuExec.scala:27-56 metric surface role)."""
    s = tpu_session()
    df = make_df(s)
    df.group_by("a").agg(Column(Alias(Sum(ColumnRef("b")), "x"))).collect()
    m = s.last_metrics
    assert m.get("pipeline", {}).get("programs", 0) >= 1, m
    assert "memory" in m and "spilled_to_host" in m["memory"], m
    # iterator path (pipeline off) surfaces per-op collect metrics
    s2 = tpu_session(**{"spark.rapids.sql.tpu.pipeline.enabled": False})
    df2 = make_df(s2)
    df2.group_by("a").agg(
        Column(Alias(Sum(ColumnRef("b")), "x"))).collect()
    m2 = s2.last_metrics
    assert m2.get("collect", {}).get("batches", 0) >= 1, m2


def test_canonical_plan_reuse():
    """Structurally identical plans (rebuilt DataFrames, repeated count())
    share one physical plan and its compiled kernels — the plan
    canonicalization / reuse role."""
    s = tpu_session()
    df = make_df(s)
    g1 = df.group_by("a").sum("b")
    g2 = df.group_by("a").sum("b")
    assert s.plan_physical(g1.plan) is s.plan_physical(g2.plan)
    # different conf state -> different physical plan
    s.conf.set("spark.rapids.sql.exec.Aggregate", False)
    assert s.plan_physical(g1.plan) is not None
    s.conf.set("spark.rapids.sql.exec.Aggregate", True)
    # different plan shape -> miss
    g3 = df.group_by("a").sum("b").filter(Column(ColumnRef("a")) > 1)
    assert s.plan_physical(g3.plan) is not s.plan_physical(g1.plan)


# ---------------------------------------------------------------------------
# count(DISTINCT x): the two-level distinct-aggregate rewrite
# ---------------------------------------------------------------------------


def _cd_df(s, n=200):
    import numpy as np
    rng = np.random.RandomState(7)
    cats = ["a", "b", "c", None, "dd"]
    return s.create_dataframe({
        "k": (T.INT, rng.randint(0, 4, n)),
        "v": (T.STRING, [cats[i] for i in rng.randint(0, len(cats), n)]),
        "w": (T.LONG, [None if i % 11 == 0 else int(x) for i, x in
                       enumerate(rng.randint(0, 100, n))]),
    }, num_partitions=3)


def test_count_distinct_alone():
    from spark_rapids_tpu import functions as F
    assert_tpu_cpu_equal(
        lambda s: _cd_df(s).group_by("k").agg(
            F.count_distinct("v").alias("cd")))


def test_count_distinct_with_other_aggs():
    from spark_rapids_tpu import functions as F
    assert_tpu_cpu_equal(
        lambda s: _cd_df(s).group_by("k").agg(
            F.count_distinct("v").alias("cd"),
            F.sum("w").alias("sw"),
            F.count("w").alias("cw"),
            F.min("w").alias("mn"),
            F.max("w").alias("mx")))


def test_count_distinct_with_avg():
    from spark_rapids_tpu import functions as F
    assert_tpu_cpu_equal(
        lambda s: _cd_df(s).group_by("k").agg(
            F.avg("w").alias("aw"),
            F.count_distinct("v").alias("cd")),
        approx=True)


def test_count_distinct_global():
    from spark_rapids_tpu import functions as F
    assert_tpu_cpu_equal(
        lambda s: _cd_df(s).agg(F.count_distinct("v").alias("cd"),
                                F.sum("w").alias("sw")))


def test_count_distinct_int_col_twice():
    from spark_rapids_tpu import functions as F
    assert_tpu_cpu_equal(
        lambda s: _cd_df(s).group_by("v").agg(
            F.count_distinct("w").alias("cd1"),
            F.count_distinct(F.col("w")).alias("cd2")))


def test_count_distinct_mixed_columns_rejected():
    import pytest
    from spark_rapids_tpu import functions as F
    from tests.compare import tpu_session
    s = tpu_session()
    df = _cd_df(s)
    with pytest.raises(NotImplementedError):
        df.group_by("k").agg(F.count_distinct("v"),
                             F.count_distinct("w"))
