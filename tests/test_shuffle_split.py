"""Shuffle split engine v2 (one-sync coalescing split) tests.

Bit-parity vs the v1 per-batch path and the CPU oracle across
{hash, range, round-robin} x {int, string, array} columns, piece-count
<= N, the B=4/N=8 dispatch-economics proof (~B+N dispatches, exactly 1
host sync), the coalesce-cap fallback, and plan/semaphore balance.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import (
    HostBatch, device_to_host_many, host_to_device,
)
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exprs.base import ColumnRef, SortOrder
from spark_rapids_tpu.kernels.layout import (
    gather_segments_kway_run, take_head,
)
from spark_rapids_tpu.parallel.exchange import (
    CpuShuffleExchangeExec, TpuShuffleExchangeExec, _sample_device_keys,
)
from spark_rapids_tpu.parallel.partitioning import (
    HashPartitioning, RangePartitioning, RoundRobinPartitioning,
)
from spark_rapids_tpu.plan.physical import ExecContext, TpuExec
from spark_rapids_tpu.runtime.device import DeviceRuntime
from spark_rapids_tpu.session import TpuSparkSession

NO_COLLAPSE = {"spark.rapids.sql.tpu.exchange.collapseLocal": False}
V1_CONF = {"spark.rapids.sql.tpu.exchange.splitV2.enabled": False}


def _mixed_pydict(rows, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "k": (T.INT, [int(x) for x in rng.randint(0, 23, rows)]),
        "v": (T.INT, list(range(rows))),
        "s": (T.STRING, [f"key{i % 11}" + "pad" * (i % 4)
                         for i in range(rows)]),
        "a": (T.ArrayType(T.INT), [[i % 3, i % 7, i % 5][: 1 + i % 3]
                                   for i in range(rows)]),
    }


class _Source(TpuExec):
    """Stub child: yields pre-staged device batches, one list per input
    partition — gives the split tests exact control over B."""

    def __init__(self, schema, parts):
        super().__init__([], schema)
        self._parts = parts

    def partitions(self, ctx):
        return [iter(list(p)) for p in self._parts]


def _drive_split(partitioning, device_parts, extra_conf=None):
    """Run TpuShuffleExchangeExec.partitions over the given device batch
    partitions; returns (rows per target partition, split metrics)."""
    conf = RapidsConf({"spark.rapids.sql.enabled": True, **NO_COLLAPSE,
                       **(extra_conf or {})})
    schema = device_parts[0][0].schema
    ex = TpuShuffleExchangeExec(partitioning, _Source(schema, device_parts))
    ctx = ExecContext(conf, device=DeviceRuntime.get(conf).device)
    parts = ex.partitions(ctx)
    rows_per_part = []
    for gen in parts:
        batches = list(gen)
        rows = []
        for hb in device_to_host_many(batches):
            d = hb.to_pydict()
            rows.extend(zip(*[d[f.name] for f in hb.schema.fields]))
        rows_per_part.append([tuple(tuple(v) if isinstance(v, list) else v
                                    for v in r) for r in rows])
    metrics = {name: m.value
               for name, m in ctx.metrics.get(ex.op_id, {}).items()}
    ctx.close_deferred()
    return rows_per_part, metrics


def _parts_of(pydicts):
    return [[host_to_device(HostBatch.from_pydict(d))] for d in pydicts]


def _partitioning(kind, n):
    if kind == "hash":
        return HashPartitioning([ColumnRef("k", T.INT)], n)
    if kind == "roundrobin":
        return RoundRobinPartitioning(n)
    p = RangePartitioning([SortOrder(ColumnRef("k", T.INT))], [0], n)
    p.prepare([(k,) for k in range(23)])
    return p


@pytest.mark.parametrize("kind", ["hash", "range", "roundrobin"])
def test_split_v2_matches_v1_mixed_columns(kind):
    """Bit parity v2 vs v1 over int + string + array columns (incl. row
    order WITHIN each target partition), and piece count <= N for v2."""
    DeviceRuntime.reset()
    try:
        n = 4
        pydicts = [_mixed_pydict(60, seed=i) for i in range(3)]
        v2_rows, v2_m = _drive_split(_partitioning(kind, n),
                                     _parts_of(pydicts))
        v1_rows, v1_m = _drive_split(_partitioning(kind, n),
                                     _parts_of(pydicts), V1_CONF)
        assert v2_rows == v1_rows
        assert sum(len(p) for p in v2_rows) == 180
        assert v2_m["shufflePieces"] <= n
        assert v2_m["shuffleSyncs"] == 1
        assert v1_m["shuffleSyncs"] == 3  # one per input batch
    finally:
        DeviceRuntime.reset()


@pytest.mark.parametrize("kind", ["hash", "range", "roundrobin"])
def test_split_v2_matches_cpu_oracle(kind):
    """End-to-end: a non-collapsed v2 exchange produces the same rows as
    the CPU engine (and as v1) for each partitioning strategy."""
    data = {"k": [(i * 37) % 23 for i in range(600)],
            "v": list(range(600)),
            "s": [f"val{i % 17}x{i % 5}" for i in range(600)]}

    def make(s):
        df = s.create_dataframe(data, num_partitions=3)
        if kind == "hash":
            return df.group_by("k").sum("v")
        if kind == "range":
            return df.order_by("s", "v")
        return df.repartition(4)

    base = {"spark.rapids.sql.enabled": True,
            "spark.sql.shuffle.partitions": 4, **NO_COLLAPSE}
    s2 = TpuSparkSession(RapidsConf(dict(base)))
    got2 = make(s2).collect()
    assert s2.last_metrics["shuffleSyncs"] >= 1  # split v2 actually ran
    s1 = TpuSparkSession(RapidsConf(dict(base, **V1_CONF)))
    got1 = make(s1).collect()
    want = make(TpuSparkSession(
        RapidsConf({"spark.rapids.sql.enabled": False}))).collect()
    if kind == "range":  # order_by output order is the contract
        assert got2 == want
        assert got1 == want
    else:
        assert sorted(got2) == sorted(want)
        assert sorted(got1) == sorted(want)


def test_split_v2_dispatch_economics_b4_n8():
    """The acceptance proof: a B=4 / N=8 shuffle split pays exactly ONE
    host sync and B+N dispatches under v2, where v1 pays B syncs and
    B*(1+N) dispatches with B*N pieces."""
    DeviceRuntime.reset()
    try:
        B, N = 4, 8
        # 256 rows per batch, round-robin: every batch feeds all 8 targets
        pydicts = [{"v": (T.INT, [int(x) for x in range(256)])}
                   for _ in range(B)]
        v2_rows, v2_m = _drive_split(RoundRobinPartitioning(N),
                                     _parts_of(pydicts))
        v1_rows, v1_m = _drive_split(RoundRobinPartitioning(N),
                                     _parts_of(pydicts), V1_CONF)
        assert v2_rows == v1_rows  # bit-identical split output
        assert v2_m["shuffleSyncs"] == 1
        assert v2_m["shuffleSplitDispatches"] == B + N
        assert v2_m["shufflePieces"] == N
        assert v1_m["shuffleSyncs"] == B
        assert v1_m["shuffleSplitDispatches"] == B * (1 + N)
        assert v1_m["shufflePieces"] == B * N
    finally:
        DeviceRuntime.reset()


def test_split_v2_coalesce_cap_falls_back_to_per_batch_pieces():
    """A target partition whose coalesced size exceeds
    splitCoalesceMaxBytes keeps per-batch pieces (spillable early), with
    identical rows and still exactly one sync."""
    DeviceRuntime.reset()
    try:
        n = 4
        pydicts = [_mixed_pydict(50, seed=i) for i in range(3)]
        cap1 = {"spark.rapids.sql.tpu.exchange.splitCoalesceMaxBytes": 1}
        capped_rows, capped_m = _drive_split(
            RoundRobinPartitioning(n), _parts_of(pydicts), cap1)
        v2_rows, v2_m = _drive_split(RoundRobinPartitioning(n),
                                     _parts_of(pydicts))
        assert capped_rows == v2_rows
        assert capped_m["shuffleSyncs"] == 1
        assert v2_m["shufflePieces"] == n
        assert capped_m["shufflePieces"] == 3 * n  # one piece per (batch, p)
    finally:
        DeviceRuntime.reset()


def test_gather_segments_kway_live_bytes():
    """Kernel-level live-bytes lesson (PR-3): segments gathered from a
    take_head-truncated batch must read offsets[start..start+count], not
    the stale dead-row bytes past num_rows."""
    full = host_to_device(HostBatch.from_pydict({
        "s": (T.STRING, ["aa", "bbbb", "cc", "dddddd", "e", "ff"]),
        "a": (T.ArrayType(T.INT), [[1], [2, 3], [4, 5, 6], [7], [], [8]]),
    }))
    trunc = take_head(full, 4)  # num_rows=4; offsets still cover 6 rows
    other = host_to_device(HostBatch.from_pydict({
        "s": (T.STRING, ["xx", "yyy"]),
        "a": (T.ArrayType(T.INT), [[9, 9], [10]]),
    }))
    out = gather_segments_kway_run([trunc, other], [1, 0], [3, 2],
                                   out_capacity=8,
                                   out_byte_caps=[64, 64])
    got = device_to_host_many([out])[0].to_pydict()
    assert got["s"] == ["bbbb", "cc", "dddddd", "xx", "yyy"]
    assert got["a"] == [[2, 3], [4, 5, 6], [7], [9, 9], [10]]


def test_range_bound_words_match_eager_path():
    """encode_bounds_device + device_partition_ids_from_words (the
    compiled range path) assigns every row the same pid as the eager
    per-bound encode loop."""
    batch = host_to_device(HostBatch.from_pydict({
        "k": (T.INT, [5, 0, 19, 7, None, 22, 11, 3]),
        "s": (T.STRING, ["m", "a", "z", "p", "q", "zz", "n", "b"]),
    }))
    p = RangePartitioning(
        [SortOrder(ColumnRef("s", T.STRING)), SortOrder(ColumnRef("k", T.INT))],
        [1, 0], 4)
    p.prepare([(f"{chr(97 + i % 26)}", i) for i in range(40)])
    eager = np.asarray(p.device_partition_ids(batch, 0))
    words = p.encode_bounds_device()
    assert len(words) >= 1
    compiled = np.asarray(
        p.device_partition_ids_from_words(batch, words))
    live = int(batch.num_rows)
    assert (eager[:live] == compiled[:live]).all()


def test_cpu_split_argsort_preserves_row_order():
    """Satellite: the argsort+np.split CPU split yields, per target, the
    batch's matching rows in ORIGINAL order (what the old boolean-mask
    scan produced and the compare harness relies on)."""
    n = 4
    hb = HostBatch.from_pydict(_mixed_pydict(80, seed=3))
    part = HashPartitioning([ColumnRef("k", T.INT)], n)
    ex = CpuShuffleExchangeExec(part, _Source(hb.schema, []))
    ex.children[0].partitions = lambda ctx: [iter([hb])]
    conf = RapidsConf({"spark.rapids.sql.enabled": False, **NO_COLLAPSE})
    ctx = ExecContext(conf)
    got = [list(p) for p in ex.partitions(ctx)]
    ids = part.host_partition_ids(hb, 0)
    for p in range(n):
        want = [tuple(c.to_list()[r] for c in hb.columns)
                for r in range(hb.num_rows) if ids[r] == p]
        rows = []
        for out_hb in got[p]:
            cols = [c.to_list() for c in out_hb.columns]
            rows.extend(zip(*cols))
        assert [tuple(r) for r in rows] == want


def test_sample_device_keys_gathers_on_device():
    """Satellite: range sampling transfers at most `limit` rows (gathered
    on device), and returns the same head rows the full-transfer path
    did."""
    batches = [[host_to_device(HostBatch.from_pydict({
        "k": (T.INT, list(range(i * 100, i * 100 + 50))),
        "s": (T.STRING, [f"s{j}" for j in range(50)]),
    }))] for i in range(3)]
    rows = _sample_device_keys(batches, [0, 1], limit=70)
    assert len(rows) == 70
    assert rows[0] == (0, "s0")
    assert rows[49] == (49, "s49")
    assert rows[50] == (100, "s0")  # second batch's head
    all_rows = _sample_device_keys(batches, [0], limit=10_000)
    assert len(all_rows) == 150


def test_split_v2_semaphore_balance():
    """Plan-verify balance on the coalesced path: after a non-collapsed
    v2 query the TPU semaphore holds nothing (held_depth()==0) — the
    split registered/closed every piece through the deferred-handle
    protocol."""
    s = TpuSparkSession(RapidsConf({
        "spark.rapids.sql.enabled": True,
        "spark.sql.shuffle.partitions": 4, **NO_COLLAPSE}))
    df = s.create_dataframe(
        {"k": [i % 9 for i in range(500)], "v": list(range(500))},
        num_partitions=3)
    assert len(df.group_by("k").sum("v").collect()) == 9
    assert s.last_metrics["shuffleSyncs"] >= 1
    assert s.runtime.semaphore.held_depth() == 0
