"""Test harness: run everything on a virtual 8-device CPU mesh so multi-chip
sharding logic is exercised without TPU hardware (SURVEY.md environment
notes).

Real-chip mode: ``SPARK_RAPIDS_TEST_PLATFORM=tpu`` skips the CPU forcing so
the same compare suites execute against the actual TPU backend (the CPU
oracle side of each compare still runs in numpy).  Double-precision results
then go through XLA's f64 emulation (~48-bit mantissa — see
docs/compatibility.md "Double precision on TPU"), so float comparisons are
relaxed to the tolerances below.

Must configure XLA before jax initializes its backends.
"""

import os

TEST_PLATFORM = os.environ.get("SPARK_RAPIDS_TEST_PLATFORM", "cpu")

if TEST_PLATFORM != "tpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import jax
import numpy as np
import pytest

if TEST_PLATFORM != "tpu":
    # The environment's sitecustomize pins JAX_PLATFORMS to the TPU plugin;
    # the config update (post-import, pre-backend-init) reliably forces CPU
    # for tests.
    jax.config.update("jax_platforms", "cpu")

import spark_rapids_tpu  # noqa: F401  (enables x64)

# f64 emulation on TPU carries ~48 mantissa bits; aggregations also reorder
# float reductions.  CPU mode keeps tight tolerances.
FLOAT_REL = 1e-4 if TEST_PLATFORM == "tpu" else 1e-6
FLOAT_ABS = 1e-6 if TEST_PLATFORM == "tpu" else 1e-9


@pytest.fixture
def rng():
    return np.random.RandomState(42)


def assert_cols_equal(expected, actual, approx=False, msg=""):
    """Deep-compare two column value lists (None = NULL)."""
    assert len(expected) == len(actual), \
        f"{msg}: row count {len(expected)} != {len(actual)}"
    approx = approx or TEST_PLATFORM == "tpu"
    for i, (e, a) in enumerate(zip(expected, actual)):
        if e is None or a is None:
            assert e is None and a is None, f"{msg} row {i}: {e!r} != {a!r}"
        elif approx and isinstance(e, float):
            if e != e:  # NaN
                assert a != a, f"{msg} row {i}: {e!r} != {a!r}"
            else:
                assert a == pytest.approx(e, rel=FLOAT_REL, abs=FLOAT_ABS), \
                    f"{msg} row {i}: {e!r} != {a!r}"
        else:
            assert e == a, f"{msg} row {i}: {e!r} != {a!r}"


def assert_batches_equal(expected, actual, approx=False, ignore_order=False):
    """Compare two HostBatch-like pydicts."""
    e, a = expected, actual
    approx = approx or TEST_PLATFORM == "tpu"
    assert set(e.keys()) == set(a.keys()), f"{e.keys()} != {a.keys()}"
    if ignore_order:
        def keyed(d):
            cols = list(d.keys())
            rows = list(zip(*[d[c] for c in cols]))
            return sorted(rows, key=lambda r: tuple(
                (x is None, str(x)) for x in r))
        er = keyed(e)
        ar = keyed(a)
        assert len(er) == len(ar), f"row count {len(er)} != {len(ar)}"
        for i, (re_, ra) in enumerate(zip(er, ar)):
            for c, (x, y) in enumerate(zip(re_, ra)):
                if approx and isinstance(x, float) and x is not None \
                        and y is not None:
                    if x != x:
                        assert y != y
                    else:
                        assert y == pytest.approx(
                            x, rel=FLOAT_REL, abs=FLOAT_ABS), \
                            f"row {i} col {c}: {x!r} != {y!r}"
                else:
                    assert (x is None) == (y is None) and (
                        x is None or x == y or
                        (approx and isinstance(x, float)
                         and y == pytest.approx(
                             x, rel=FLOAT_REL, abs=FLOAT_ABS))), \
                        f"row {i} col {c}: {x!r} != {y!r}"
    else:
        for name in e:
            assert_cols_equal(e[name], a[name], approx=approx, msg=name)
