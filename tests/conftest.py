"""Test harness: run everything on a virtual 8-device CPU mesh so multi-chip
sharding logic is exercised without TPU hardware (SURVEY.md environment
notes).

Real-chip mode: ``SPARK_RAPIDS_TEST_PLATFORM=tpu`` skips the CPU forcing so
the same compare suites execute against the actual TPU backend (the CPU
oracle side of each compare still runs in numpy).  Double-precision results
then go through XLA's f64 emulation (~48-bit mantissa — see
docs/compatibility.md "Double precision on TPU"), so float comparisons are
relaxed to the tolerances below.

Must configure XLA before jax initializes its backends.
"""

import os

TEST_PLATFORM = os.environ.get("SPARK_RAPIDS_TEST_PLATFORM", "cpu")

if TEST_PLATFORM != "tpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import jax
import numpy as np
import pytest

if TEST_PLATFORM != "tpu":
    # The environment's sitecustomize pins JAX_PLATFORMS to the TPU plugin;
    # the config update (post-import, pre-backend-init) reliably forces CPU
    # for tests.
    jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache across suite runs: the suite is
# compile-bound (every test's fresh execs re-jit), and cached executables
# cut repeat-run wall time substantially.  Content-addressed, safe to
# share; delete the directory to force cold compiles.
_XLA_CACHE = os.environ.get("SPARK_RAPIDS_TEST_XLA_CACHE",
                            "/tmp/rapids_tpu_test_xla_cache")

import spark_rapids_tpu  # noqa: F401  (enables x64)

if _XLA_CACHE:
    from spark_rapids_tpu.utils.compile_registry import (
        enable_persistent_cache,
    )
    enable_persistent_cache(_XLA_CACHE, min_compile_secs=0.5)

# f64 emulation on TPU carries ~48 mantissa bits; aggregations also reorder
# float reductions.  CPU mode keeps tight tolerances.
FLOAT_REL = 1e-4 if TEST_PLATFORM == "tpu" else 1e-6
FLOAT_ABS = 1e-6 if TEST_PLATFORM == "tpu" else 1e-9


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running integration tests excluded from the quick "
        "(-m 'not slow') tier-1 pass; still run by a direct invocation")


# Per-test wall-clock bound (ci/run_ci.sh exports PYTEST_PER_TEST_TIMEOUT):
# a wedged test — historically a cross-suite state leak around test #262 —
# fails loudly with a TimeoutError instead of hanging the whole run.
# SIGALRM-based (tests execute on the main thread); 0/unset disables.
_PER_TEST_TIMEOUT = float(os.environ.get("PYTEST_PER_TEST_TIMEOUT", "0") or 0)

if _PER_TEST_TIMEOUT > 0:
    import signal

    @pytest.hookimpl(hookwrapper=True)
    def pytest_runtest_call(item):
        def on_timeout(signum, frame):
            import faulthandler
            import sys
            # all-thread stacks: the wedged thread is usually NOT the main
            # thread (e.g. a stage worker stuck in a device transfer)
            faulthandler.dump_traceback(file=sys.stderr)
            raise TimeoutError(
                f"test exceeded PYTEST_PER_TEST_TIMEOUT="
                f"{_PER_TEST_TIMEOUT:g}s (wedged? check for leaked "
                f"worker threads / device state from earlier tests)")

        old = signal.signal(signal.SIGALRM, on_timeout)
        signal.setitimer(signal.ITIMER_REAL, _PER_TEST_TIMEOUT)
        try:
            yield
        finally:
            signal.setitimer(signal.ITIMER_REAL, 0)
            signal.signal(signal.SIGALRM, old)


# Plan-invariant verification (RAPIDS_PLAN_VERIFY=1 — ci/run_ci.sh turns
# it on): wrap TpuSparkSession.execute so every plan the suite runs is
# structurally verified after collection — schema/transition consistency,
# donation-mask provenance, semaphore balance (analysis/plan_verify.py).
# Runs on the executed plan objects, so it costs microseconds per query.
if os.environ.get("RAPIDS_PLAN_VERIFY") == "1":
    from spark_rapids_tpu.analysis import plan_verify as _plan_verify
    from spark_rapids_tpu.session import TpuSparkSession as _TpuSession

    _orig_execute = _TpuSession.execute

    def _verified_execute(self, plan):
        out = _orig_execute(self, plan)
        _plan_verify.verify_session(self)
        return out

    _TpuSession.execute = _verified_execute


@pytest.fixture
def rng():
    return np.random.RandomState(42)


def assert_cols_equal(expected, actual, approx=False, msg=""):
    """Deep-compare two column value lists (None = NULL)."""
    assert len(expected) == len(actual), \
        f"{msg}: row count {len(expected)} != {len(actual)}"
    approx = approx or TEST_PLATFORM == "tpu"
    for i, (e, a) in enumerate(zip(expected, actual)):
        if e is None or a is None:
            assert e is None and a is None, f"{msg} row {i}: {e!r} != {a!r}"
        elif approx and isinstance(e, float):
            if e != e:  # NaN
                assert a != a, f"{msg} row {i}: {e!r} != {a!r}"
            else:
                assert a == pytest.approx(e, rel=FLOAT_REL, abs=FLOAT_ABS), \
                    f"{msg} row {i}: {e!r} != {a!r}"
        else:
            assert e == a, f"{msg} row {i}: {e!r} != {a!r}"


def assert_batches_equal(expected, actual, approx=False, ignore_order=False):
    """Compare two HostBatch-like pydicts."""
    e, a = expected, actual
    approx = approx or TEST_PLATFORM == "tpu"
    assert set(e.keys()) == set(a.keys()), f"{e.keys()} != {a.keys()}"
    if ignore_order:
        def keyed(d):
            cols = list(d.keys())
            rows = list(zip(*[d[c] for c in cols]))
            return sorted(rows, key=lambda r: tuple(
                (x is None, str(x)) for x in r))
        er = keyed(e)
        ar = keyed(a)
        assert len(er) == len(ar), f"row count {len(er)} != {len(ar)}"
        for i, (re_, ra) in enumerate(zip(er, ar)):
            for c, (x, y) in enumerate(zip(re_, ra)):
                if approx and isinstance(x, float) and x is not None \
                        and y is not None:
                    if x != x:
                        assert y != y
                    else:
                        assert y == pytest.approx(
                            x, rel=FLOAT_REL, abs=FLOAT_ABS), \
                            f"row {i} col {c}: {x!r} != {y!r}"
                else:
                    assert (x is None) == (y is None) and (
                        x is None or x == y or
                        (approx and isinstance(x, float)
                         and y == pytest.approx(
                             x, rel=FLOAT_REL, abs=FLOAT_ABS))), \
                        f"row {i} col {c}: {x!r} != {y!r}"
    else:
        for name in e:
            assert_cols_equal(e[name], a[name], approx=approx, msg=name)
