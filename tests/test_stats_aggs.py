"""stddev/variance aggregate family + DataFrame.describe."""

import math

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T

from compare import assert_tpu_cpu_equal, tpu_session

DATA = {"g": (T.STRING, ["a", "a", "a", "b", "b", "c", "d"]),
        "x": (T.DOUBLE, [1.0, 2.0, 4.0, 10.0, 30.0, 5.0, None])}


def test_stddev_variance_ground_truth():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    rows = (df.group_by("g")
            .agg(F.stddev("x").alias("sd"),
                 F.stddev_pop("x").alias("sp"),
                 F.variance("x").alias("v"),
                 F.var_pop("x").alias("vp"))
            .order_by("g").collect())
    by_g = {r[0]: r[1:] for r in rows}
    a = [1.0, 2.0, 4.0]
    assert by_g["a"][0] == pytest.approx(np.std(a, ddof=1))
    assert by_g["a"][1] == pytest.approx(np.std(a, ddof=0))
    assert by_g["a"][2] == pytest.approx(np.var(a, ddof=1))
    assert by_g["a"][3] == pytest.approx(np.var(a, ddof=0))
    # single-row group: sample variants are NaN, population 0.0
    assert math.isnan(by_g["c"][0]) and math.isnan(by_g["c"][2])
    assert by_g["c"][1] == 0.0 and by_g["c"][3] == 0.0
    # all-null group: NULL everywhere
    assert by_g["d"] == (None, None, None, None)


def test_stddev_engines_agree_multi_partition():
    def build(s):
        df = s.create_dataframe(DATA, num_partitions=3)
        return (df.group_by("g")
                .agg(F.stddev("x").alias("sd"),
                     F.var_pop("x").alias("vp"),
                     F.count("x").alias("n"))
                .order_by("g"))

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_stddev_sql():
    def build(s):
        s.register_view("t", s.create_dataframe(DATA, num_partitions=2))
        return s.sql("SELECT g, stddev(x) AS sd, var_pop(x) AS vp "
                     "FROM t GROUP BY g ORDER BY g")

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_stddev_merge_across_shuffle():
    """Partial/merge correctness: many partitions force the Chan-merge
    path; agree with numpy over the whole column."""
    rng = np.random.RandomState(7)
    vals = (rng.rand(4000) * 100).round(3)
    s = tpu_session()
    df = s.create_dataframe({"x": (T.DOUBLE, vals)}, num_partitions=6)
    row = df.agg(F.stddev("x").alias("sd"),
                 F.var_pop("x").alias("vp")).collect()[0]
    assert row[0] == pytest.approx(float(np.std(vals, ddof=1)), rel=1e-9)
    assert row[1] == pytest.approx(float(np.var(vals, ddof=0)), rel=1e-9)


def test_describe():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    out = df.describe()
    assert out.columns == ["summary", "g", "x"]
    rows = dict((r[0], r[2]) for r in out.collect())
    assert rows["count"] == "6"
    assert float(rows["mean"]) == pytest.approx(np.mean(
        [1.0, 2.0, 4.0, 10.0, 30.0, 5.0]))
    assert float(rows["min"]) == 1.0 and float(rows["max"]) == 30.0


def test_stddev_large_mean_no_cancellation():
    """Two-pass m2: epoch-scale values must not cancel to 0."""
    base = 6.4e9
    vals = [base + 0.001, base + 0.002, base + 0.003, base + 0.004]
    s = tpu_session()
    df = s.create_dataframe({"x": (T.DOUBLE, vals)}, num_partitions=2)
    row = df.agg(F.stddev("x").alias("sd")).collect()[0]
    assert row[0] == pytest.approx(float(np.std(vals, ddof=1)), rel=1e-3)


def test_describe_strings_and_empty():
    s = tpu_session()
    df = s.create_dataframe({"a": (T.STRING, ["x", "y", None])},
                            num_partitions=1)
    rows = dict((r[0], r[1]) for r in df.describe().collect())
    assert rows["count"] == "2" and rows["min"] == "x" \
        and rows["max"] == "y" and rows["mean"] is None


BDATA = {"g": (T.STRING, ["a", "a", "a", "a", "b", "b", "c", "d", "d"]),
         "x": (T.DOUBLE, [1.0, 2.0, 3.0, None, 5.0, 7.0, 4.0, None,
                          None]),
         "y": (T.DOUBLE, [2.0, 4.1, 5.9, 9.0, 1.0, None, 8.0, 1.0,
                          None])}


def test_corr_covar_ground_truth():
    s = tpu_session()
    df = s.create_dataframe(BDATA, num_partitions=2)
    rows = (df.group_by("g")
            .agg(F.corr("x", "y").alias("r"),
                 F.covar_pop("x", "y").alias("cp"),
                 F.covar_samp("x", "y").alias("cs"),
                 F.count("x").alias("n"))
            .order_by("g").collect())
    by_g = {r[0]: r[1:] for r in rows}
    # group a: pair-complete rows are (1,2),(2,4.1),(3,5.9)
    xs, ys = np.array([1.0, 2.0, 3.0]), np.array([2.0, 4.1, 5.9])
    assert by_g["a"][0] == pytest.approx(float(np.corrcoef(xs, ys)[0, 1]))
    assert by_g["a"][1] == pytest.approx(
        float(np.cov(xs, ys, ddof=0)[0, 1]))
    assert by_g["a"][2] == pytest.approx(
        float(np.cov(xs, ys, ddof=1)[0, 1]))
    # group b: single complete pair -> corr NaN, covar_pop 0, samp NaN
    assert math.isnan(by_g["b"][0]) and by_g["b"][1] == 0.0
    assert math.isnan(by_g["b"][2])
    # group c: single pair as well
    assert by_g["c"][1] == 0.0
    # group d: no complete pairs -> NULL everywhere
    assert by_g["d"][:3] == (None, None, None)


def test_corr_engines_agree_and_sql():
    def build(s):
        df = s.create_dataframe(BDATA, num_partitions=3)
        return (df.group_by("g")
                .agg(F.corr("x", "y").alias("r"),
                     F.covar_samp("x", "y").alias("cs"),
                     F.sum("x").alias("sx"))
                .order_by("g"))

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)

    def build_sql(s):
        s.register_view("t", s.create_dataframe(BDATA, num_partitions=2))
        return s.sql("SELECT g, corr(x, y) AS r, covar_pop(x, y) AS cp "
                     "FROM t GROUP BY g ORDER BY g")

    assert_tpu_cpu_equal(build_sql, approx=True, ignore_order=False)


def test_corr_constant_series_is_nan():
    s = tpu_session()
    df = s.create_dataframe(
        {"x": (T.DOUBLE, [3.0, 3.0, 3.0]),
         "y": (T.DOUBLE, [1.0, 2.0, 3.0])}, num_partitions=1)
    row = df.agg(F.corr("x", "y").alias("r")).collect()[0]
    assert math.isnan(row[0])  # zero variance -> NaN (Spark)
