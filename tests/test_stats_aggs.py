"""stddev/variance aggregate family + DataFrame.describe."""

import math

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T

from compare import assert_tpu_cpu_equal, tpu_session

DATA = {"g": (T.STRING, ["a", "a", "a", "b", "b", "c", "d"]),
        "x": (T.DOUBLE, [1.0, 2.0, 4.0, 10.0, 30.0, 5.0, None])}


def test_stddev_variance_ground_truth():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    rows = (df.group_by("g")
            .agg(F.stddev("x").alias("sd"),
                 F.stddev_pop("x").alias("sp"),
                 F.variance("x").alias("v"),
                 F.var_pop("x").alias("vp"))
            .order_by("g").collect())
    by_g = {r[0]: r[1:] for r in rows}
    a = [1.0, 2.0, 4.0]
    assert by_g["a"][0] == pytest.approx(np.std(a, ddof=1))
    assert by_g["a"][1] == pytest.approx(np.std(a, ddof=0))
    assert by_g["a"][2] == pytest.approx(np.var(a, ddof=1))
    assert by_g["a"][3] == pytest.approx(np.var(a, ddof=0))
    # single-row group: sample variants are NaN, population 0.0
    assert math.isnan(by_g["c"][0]) and math.isnan(by_g["c"][2])
    assert by_g["c"][1] == 0.0 and by_g["c"][3] == 0.0
    # all-null group: NULL everywhere
    assert by_g["d"] == (None, None, None, None)


def test_stddev_engines_agree_multi_partition():
    def build(s):
        df = s.create_dataframe(DATA, num_partitions=3)
        return (df.group_by("g")
                .agg(F.stddev("x").alias("sd"),
                     F.var_pop("x").alias("vp"),
                     F.count("x").alias("n"))
                .order_by("g"))

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_stddev_sql():
    def build(s):
        s.register_view("t", s.create_dataframe(DATA, num_partitions=2))
        return s.sql("SELECT g, stddev(x) AS sd, var_pop(x) AS vp "
                     "FROM t GROUP BY g ORDER BY g")

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_stddev_merge_across_shuffle():
    """Partial/merge correctness: many partitions force the Chan-merge
    path; agree with numpy over the whole column."""
    rng = np.random.RandomState(7)
    vals = (rng.rand(4000) * 100).round(3)
    s = tpu_session()
    df = s.create_dataframe({"x": (T.DOUBLE, vals)}, num_partitions=6)
    row = df.agg(F.stddev("x").alias("sd"),
                 F.var_pop("x").alias("vp")).collect()[0]
    assert row[0] == pytest.approx(float(np.std(vals, ddof=1)), rel=1e-9)
    assert row[1] == pytest.approx(float(np.var(vals, ddof=0)), rel=1e-9)


def test_describe():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    out = df.describe()
    assert out.columns == ["summary", "g", "x"]
    rows = dict((r[0], r[2]) for r in out.collect())
    assert rows["count"] == "6"
    assert float(rows["mean"]) == pytest.approx(np.mean(
        [1.0, 2.0, 4.0, 10.0, 30.0, 5.0]))
    assert float(rows["min"]) == 1.0 and float(rows["max"]) == 30.0


def test_stddev_large_mean_no_cancellation():
    """Two-pass m2: epoch-scale values must not cancel to 0."""
    base = 6.4e9
    vals = [base + 0.001, base + 0.002, base + 0.003, base + 0.004]
    s = tpu_session()
    df = s.create_dataframe({"x": (T.DOUBLE, vals)}, num_partitions=2)
    row = df.agg(F.stddev("x").alias("sd")).collect()[0]
    assert row[0] == pytest.approx(float(np.std(vals, ddof=1)), rel=1e-3)


def test_describe_strings_and_empty():
    s = tpu_session()
    df = s.create_dataframe({"a": (T.STRING, ["x", "y", None])},
                            num_partitions=1)
    rows = dict((r[0], r[1]) for r in df.describe().collect())
    assert rows["count"] == "2" and rows["min"] == "x" \
        and rows["max"] == "y" and rows["mean"] is None
