"""Observability subsystem tests: event-bus epochs, ring bounds, profile
parity against last_metrics, Chrome/JSONL export, the rapidsprof CLI, and
the zero-overhead disabled path (ISSUE PR 10 acceptance list)."""

import json
import os
import subprocess
import sys

import numpy as np

from compare import tpu_session
from spark_rapids_tpu import functions as F
from spark_rapids_tpu.obs import export as obs_export
from spark_rapids_tpu.obs.events import EventBus
from spark_rapids_tpu.runtime.device import DeviceRuntime

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _simple_query(s, n=300):
    df = s.create_dataframe({"k": [i % 3 for i in range(n)],
                             "v": [float(i) for i in range(n)]})
    return df.group_by("k").agg(F.sum("v").alias("sv")).order_by("k")


def test_event_ordering_and_epoch_reset():
    """Each query drains into its own profile: per-query event counts match
    the obsEventCount metric, query ids are distinct/increasing, and the
    first profile is not mutated by the second query."""
    s = tpu_session()
    _simple_query(s).collect()
    count1 = s.last_metrics["obsEventCount"]
    p1 = s.query_history()[-1]
    assert count1 > 0
    assert p1.event_count == count1
    first_events = list(p1.events)

    df2 = s.create_dataframe({"a": list(range(100))})
    df2.filter(F.col("a") > 10).order_by("a").collect()
    count2 = s.last_metrics["obsEventCount"]
    hist = s.query_history()
    assert len(hist) == 2
    p2 = hist[-1]
    assert p2.event_count == count2
    assert p2.query_id > p1.query_id
    # epoch reset: the second query's events never leak into the first
    assert hist[0].events == first_events
    # spans carry a coherent clock: t1 >= t0 inside each event, and the
    # profile's window bounds cover every stamped span
    for p in hist:
        for ev in p.events:
            assert ev.t1 >= ev.t0
            if ev.t0:
                assert p.t_min <= ev.t0 <= p.t_max


def test_ring_overflow_increments_dropped():
    # direct bus semantics: drop-new, bounded length, counted drops
    bus = EventBus(max_events=4)
    for i in range(6):
        bus.append(object())
    events, dropped = bus.drain()
    assert len(events) == 4
    assert dropped == 2
    # drain resets
    events2, dropped2 = bus.drain()
    assert events2 == [] and dropped2 == 0

    # and through a real query with a tiny ring
    s = tpu_session(**{"spark.rapids.sql.tpu.obs.ring.maxEvents": 2})
    _simple_query(s).collect()
    assert s.last_metrics["obsEventCount"] == 2
    assert s.last_metrics["obsEventsDropped"] > 0
    assert s.query_history()[-1].dropped == \
        s.last_metrics["obsEventsDropped"]


def test_rollup_matches_last_metrics_on_shuffle_spill_query():
    """On a query that really shuffles and really spills, the profile's
    rollups reproduce the dispatch/device/shuffle/spill totals that the
    independent metric pipeline reports for the same window."""
    DeviceRuntime.reset()
    try:
        s = tpu_session(**{
            "spark.rapids.sql.tpu.exchange.collapseLocal": False,
            "spark.sql.autoBroadcastJoinThreshold": -1,
            # ~64KB device budget: far below the join working set
            "spark.rapids.memory.tpu.spillBudgetBytes": 64 * 1024,
            # synchronous spill so every spill span lands inside the
            # emitting query's epoch
            "spark.rapids.sql.tpu.spill.async.enabled": False,
        })
        n = 20_000
        rng = np.random.RandomState(7)
        left = s.create_dataframe(
            {"k": rng.randint(0, 500, n).tolist(),
             "v": rng.randint(0, 100, n).tolist()}, num_partitions=3)
        right = s.create_dataframe(
            {"k": list(range(500)), "w": list(range(500))},
            num_partitions=2)
        rows = left.join(right, on="k", how="inner").collect()
        assert len(rows) == n

        m = s.last_metrics
        p = s.query_history()[-1]
        # dispatch: one device span per compiled-program dispatch
        assert p.site("dispatch")["count"] == m["dispatchCount"]
        # device time: every nanosecond the metric pipeline charged is
        # attributed to a named operator (>=90% is the acceptance floor;
        # the spans add the exact same elapsed values, so it is exact)
        assert m["deviceTimeNs"] > 0
        assert p.attributed_device_ns == m["deviceTimeNs"]
        # shuffle: exchange split/mesh spans carry the same bytes the
        # per-op shuffleBytes metric accumulated
        assert m["shuffleBytes"] > 0
        assert sum(r["shuffle_bytes"] for r in p.op_rollups.values()) == \
            m["shuffleBytes"]
        # spill: synchronous to_host/to_disk spans carry the same bytes
        # as the catalog's per-query byte deltas
        assert m["spillToHostBytes"] > 0
        assert p.site("spill")["bytes"] == \
            m["spillToHostBytes"] + m["spillToDiskBytes"]
        # named-operator attribution: rollup names are real exec names
        top = p.top_operators(3)
        assert top and any("Exec" in (r["name"] or "") for r in top)
    finally:
        DeviceRuntime.reset()


def test_chrome_trace_valid_json_sorted():
    s = tpu_session()
    _simple_query(s).collect()
    p = s.query_history()[-1]
    doc = json.loads(json.dumps(obs_export.events_to_chrome(p.events)))
    evs = doc["traceEvents"]
    assert evs
    body = [e for e in evs if e["ph"] != "M"]
    assert body
    assert all(e["ph"] in ("X", "i", "M") for e in evs)
    # spans sorted by timestamp, durations non-negative
    ts = [e["ts"] for e in body]
    assert ts == sorted(ts)
    assert all(e.get("dur", 0) >= 0 for e in body)
    # every track has thread metadata naming its site/thread
    tids = {e["tid"] for e in body}
    meta_tids = {e["tid"] for e in evs if e["ph"] == "M"}
    assert tids <= meta_tids


def test_jsonl_roundtrip_through_rapidsprof(tmp_path):
    log_dir = str(tmp_path / "obslog")
    s = tpu_session(**{"spark.rapids.sql.tpu.obs.eventLogDir": log_dir})
    _simple_query(s).collect()
    # the dir holds the per-pid event log plus the telemetry flush
    # (telemetry-<pid>.jsonl, rapidstop's input — covered in test_obs_v2)
    logs = [os.path.join(log_dir, f) for f in os.listdir(log_dir)
            if f.startswith("events-")]
    assert len(logs) == 1

    # the log parses back into the same profile shape
    queries = obs_export.read_event_log(logs[0])
    assert len(queries) == 1
    assert queries[0]["event_count"] == s.last_metrics["obsEventCount"]
    assert len(queries[0]["events"]) == queries[0]["event_count"]

    # and the runtime-free CLI renders a report + a loadable Chrome trace
    trace = str(tmp_path / "trace.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "rapidsprof.py"),
         logs[0], "--chrome", trace],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "top operators by device time" in proc.stdout
    assert "Exec" in proc.stdout  # names at least one real operator
    with open(trace) as f:
        tdoc = json.load(f)
    assert tdoc["traceEvents"]


def test_obs_disabled_zero_events_bit_identical():
    on = tpu_session()
    off = tpu_session(**{"spark.rapids.sql.tpu.obs.enabled": False})
    rows_on = _simple_query(on).collect()
    rows_off = _simple_query(off).collect()
    assert rows_on == rows_off
    assert off.last_metrics["obsEventCount"] == 0
    assert off.last_metrics["obsEventsDropped"] == 0
    assert off.query_history() == []
    # the enabled session still profiled
    assert on.last_metrics["obsEventCount"] > 0
    assert len(on.query_history()) == 1


def test_held_depth_zero_after_profiled_query():
    """Profiling must not perturb semaphore accounting: after a profiled
    query completes, nothing still holds the device semaphore."""
    s = tpu_session()
    _simple_query(s).collect()
    assert s.query_history()
    if s.runtime is not None and s.runtime.semaphore is not None:
        assert s.runtime.semaphore.held_depth() == 0


def test_explain_last_metrics_annotates_operators():
    s = tpu_session()
    _simple_query(s).collect()
    text = s.explain_last(metrics=True)
    assert "dispatches=" in text
    assert "device=" in text
