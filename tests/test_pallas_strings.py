"""Pallas contains-scan kernel vs the XLA formulation (interpret mode on
the CPU backend; the real-TPU lowering is exercised by the chip run)."""

import numpy as np
import pytest


def _make_col(strings):
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.batch import HostBatch, host_to_device
    hb = HostBatch.from_pydict({"s": (T.STRING, strings)})
    db = host_to_device(hb)
    return db.columns[0], db.num_rows, db.capacity


@pytest.mark.parametrize("needle", ["ab", "aba", "x", "needle", "zz"])
def test_pallas_contains_matches_xla(monkeypatch, needle):
    monkeypatch.setenv("SPARK_RAPIDS_PALLAS_STRINGS", "interp")
    from spark_rapids_tpu.exprs.base import DevVal
    from spark_rapids_tpu.exprs import strings as S
    from spark_rapids_tpu.kernels import pallas_strings as PS

    rng = np.random.RandomState(7)
    alphabet = list("abnexzle")
    strs = ["".join(rng.choice(alphabet, rng.randint(0, 12)))
            for _ in range(200)]
    strs[3] = ""
    strs[5] = needle
    strs[7] = "q" + needle + "q"
    col, num_rows, cap = _make_col(strs)
    v = DevVal(col.dtype, col.data, col.validity, col.offsets)

    got = np.asarray(PS.rows_with_match(
        v.data, v.offsets, v.validity, cap, needle.encode()))
    want = np.asarray(S._find_matches_reference(v, needle.encode())) \
        if hasattr(S, "_find_matches_reference") else None
    # oracle: python substring check
    expect = np.zeros(cap, dtype=bool)
    for i, s in enumerate(strs):
        expect[i] = needle in s
    np.testing.assert_array_equal(got[:len(strs)], expect[:len(strs)])
    if want is not None:
        np.testing.assert_array_equal(got, want)


def test_pallas_boundary_no_cross(monkeypatch):
    """A needle split across two adjacent rows must NOT match."""
    monkeypatch.setenv("SPARK_RAPIDS_PALLAS_STRINGS", "interp")
    from spark_rapids_tpu.exprs.base import DevVal
    from spark_rapids_tpu.kernels import pallas_strings as PS

    strs = ["xxa", "bxx", "ab", "a", "b"]
    col, num_rows, cap = _make_col(strs)
    v = DevVal(col.dtype, col.data, col.validity, col.offsets)
    got = np.asarray(PS.rows_with_match(
        v.data, v.offsets, v.validity, cap, b"ab"))
    np.testing.assert_array_equal(
        got[:5], np.array([False, False, True, False, False]))
