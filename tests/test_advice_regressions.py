"""Regression tests for the round-1 advisor findings (ADVICE.md).

1. Long (>64-byte prefix) equal strings must group together even when a
   *different* string shares their 64-byte prefix and sits between them in
   input order (sortkeys.py tie-break words).
2. Join on long strings sharing a prefix must not cross-match.
3. lag/lead with a default on a string column must fall back to CPU (and
   therefore honour the default) instead of silently emitting NULL.
"""

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.functions import Window

from compare import assert_tpu_cpu_equal


def _long(prefix_char: str, tail: str, n: int = 80) -> str:
    return prefix_char * n + tail


class TestLongStringGrouping:
    def test_equal_long_strings_group_once_despite_prefix_collision(self):
        # a and b share an 80-char prefix; two copies of a bracket b in
        # input order.  Before the tie-break fix the stable sort could
        # leave them non-adjacent -> duplicate groups.
        a = _long("x", "AAAA")
        b = _long("x", "BBBB")
        data = {"s": [a, b, a, b, a, None, b],
                "v": [1, 10, 2, 20, 3, 100, 30]}

        def q(s):
            df = s.create_dataframe(data, num_partitions=2)
            return df.group_by("s").agg(F.sum("v").alias("sv"),
                                        F.count("v").alias("c"))

        assert_tpu_cpu_equal(q)

    def test_sorted_equal_long_strings_adjacent(self):
        a = _long("p", "1")
        b = _long("p", "2")
        c = _long("p", "3")
        data = {"s": [b, a, c, a, b, c, a], "v": list(range(7))}

        def q(s):
            df = s.create_dataframe(data, num_partitions=1)
            # window partition over s: each partition must see exactly its
            # own rows (row_number + per-partition sum)
            w = Window.partition_by("s").order_by("v")
            return df.with_column("rn", F.row_number().over(w)) \
                     .with_column("ps", F.sum("v").over(w))

        assert_tpu_cpu_equal(q)

    def test_long_string_join_no_prefix_cross_match(self):
        a = _long("k", "left")
        b = _long("k", "right")
        left = {"k": [a, b], "lv": [1, 2]}
        right = {"k": [a, b, a], "rv": [10, 20, 30]}

        def q(s):
            l = s.create_dataframe(left, num_partitions=2)
            r = s.create_dataframe(right, num_partitions=2)
            return l.join(r, on="k", how="inner")

        assert_tpu_cpu_equal(q)


class TestLagLeadStringDefault:
    def test_lag_string_default_falls_back(self):
        data = {"g": [1, 1, 1, 2, 2], "o": [1, 2, 3, 1, 2],
                "s": ["a", "b", "c", "d", "e"]}

        def q(s):
            df = s.create_dataframe(data, num_partitions=1)
            w = Window.partition_by("g").order_by("o")
            return df.with_column("p", F.lag("s", 1, "DEFAULT").over(w))

        assert_tpu_cpu_equal(q, expect_fallback="Lag")


class TestAdviceRound4:
    """Regression coverage for the round-4 advisor findings (ADVICE.md)."""

    def test_array_contains_nan_needle_matches_nan(self):
        data = {"a": [[1.0, float("nan")], [1.0, 2.0], None, [float("nan")]],
                "x": [1, 2, 3, 4]}

        def q(s):
            df = s.create_dataframe(data, num_partitions=1)
            return df.with_column(
                "hit", F.array_contains(df["a"], float("nan")))

        assert_tpu_cpu_equal(q)

    def test_array_position_nan_needle(self):
        data = {"a": [[1.0, float("nan"), 3.0], [2.0, 2.5], [float("nan")]],
                "x": [1, 2, 3]}

        def q(s):
            df = s.create_dataframe(data, num_partitions=1)
            return df.with_column(
                "pos", F.array_position(df["a"], float("nan")))

        assert_tpu_cpu_equal(q)

    def test_range_frame_desc_int64_min_no_wrap(self):
        imin = -(2 ** 63)
        data = {"g": [1, 1, 1, 1], "k": [imin, imin + 1, 5, 100],
                "v": [1, 2, 3, 4]}

        def q(s):
            df = s.create_dataframe(data, num_partitions=1)
            w = (Window.partition_by("g")
                 .order_by(df["k"].desc())
                 .range_between(-1, 1))
            return df.with_column("sv", F.sum("v").over(w))

        # int sum: no float-agg gate, so the window genuinely runs on TPU
        assert_tpu_cpu_equal(q, forbid_fallback="Window")

    def test_range_frame_asc_int64_max_no_wrap(self):
        imax = 2 ** 63 - 1
        data = {"g": [1, 1, 1, 1], "k": [imax, imax - 1, 5, 100],
                "v": [1, 2, 3, 4]}

        def q(s):
            df = s.create_dataframe(data, num_partitions=1)
            w = (Window.partition_by("g")
                 .order_by("k")
                 .range_between(-1, 1))
            return df.with_column("sv", F.sum("v").over(w))

        assert_tpu_cpu_equal(q, forbid_fallback="Window")

    def test_lz4_codec_alias_removed(self):
        import pytest
        from spark_rapids_tpu.mem.codec import get_codec
        with pytest.raises(ValueError):
            get_codec("lz4")
        assert get_codec("nativelz") is not None
