"""Adaptive execution v1 (plan/adaptive): on/off bit parity over mixed
column types, coalescing economics, dynamic shuffled->broadcast switch,
skew split on one-hot-key data, device-lost replay through a switched
join, and permit balance after every adaptive query."""

import math

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.dataframe import Column
from spark_rapids_tpu.exprs.aggregates import Count, Sum
from spark_rapids_tpu.exprs.base import Alias, ColumnRef
from spark_rapids_tpu.fault import inject

from compare import _canon, cpu_session, tpu_session

NO_COLLAPSE = {"spark.rapids.sql.tpu.exchange.collapseLocal": False}
ADAPTIVE_OFF = {"spark.rapids.sql.tpu.adaptive.enabled": False}


@pytest.fixture(autouse=True)
def _fresh_registry():
    inject.uninstall()
    yield
    inject.uninstall()


def _assert_equal_rows(a_rows, b_rows, ordered=False):
    a = _canon(a_rows, True, not ordered)
    b = _canon(b_rows, True, not ordered)
    assert len(a) == len(b), f"lhs={len(a)} rhs={len(b)}"
    for i, (ra, rb) in enumerate(zip(a, b)):
        assert ra == rb, f"row {i}: lhs={ra} rhs={rb}"


def _assert_balanced(s):
    assert s.runtime.semaphore.held_depth() == 0, \
        "leaked device admission permit"


def _metric_ops(sess, name):
    return [op for op, ms in sess.last_metrics.items()
            if isinstance(ms, dict) and name in ms]


MIXED = {
    "k": (T.INT, [i % 7 for i in range(180)]),
    "v": (T.LONG, list(range(180))),
    "s": (T.STRING, [f"name{i % 13}" + "pad" * (i % 3)
                     for i in range(180)]),
    "a": (T.ArrayType(T.LONG), [[i % 5, i % 3][: 1 + i % 2]
                                for i in range(180)]),
}
#: MIXED minus the array column: arrays force a CPU join/sort fallback
#: (nested-type envelope), so coalescing-metric tests use this schema.
FLAT = {k: v for k, v in MIXED.items() if k != "a"}
DIM = {
    "k": (T.INT, [0, 1, 2, 3, 4, 5, 6]),
    "w": (T.LONG, [10, 20, 30, 40, 50, 60, 70]),
}


def _sessions(extra=None):
    base = dict(NO_COLLAPSE, **(extra or {}))
    return (tpu_session(**base),
            tpu_session(**dict(base, **ADAPTIVE_OFF)),
            cpu_session(**base))


# -- on/off bit parity -------------------------------------------------------


def test_adaptive_onoff_parity_repartition_mixed_types():
    """Int + string + array columns through a real (non-collapsed)
    varlen shuffle: adaptive on, adaptive off and the CPU engine agree
    bit-for-bit.  (Explicit repartition(n) keeps its partition count —
    Spark AQE likewise never coalesces a user-specified repartition —
    so this pins that adaptive leaves the varlen split untouched.)"""
    def q(s):
        return s.create_dataframe(MIXED, num_partitions=3) \
            .repartition(8, "k").collect()
    on, off, cpu = _sessions()
    rows_on, rows_off, rows_cpu = q(on), q(off), q(cpu)
    _assert_equal_rows(rows_cpu, rows_on)
    _assert_equal_rows(rows_off, rows_on)
    _assert_balanced(on)
    _assert_balanced(off)


def test_adaptive_onoff_parity_coalesced_sort_strings():
    """Global sort (RangePartitioning shuffle + coalescing reader) over
    int + string columns: identical ordered rows with adaptive on, off
    and on the CPU engine, and the reader provably coalesced."""
    confs = {"spark.sql.shuffle.partitions": 8}

    def q(s):
        return s.create_dataframe(FLAT, num_partitions=3) \
            .order_by("v").collect()
    on, off, cpu = _sessions(confs)
    rows_on, rows_off, rows_cpu = q(on), q(off), q(cpu)
    _assert_equal_rows(rows_cpu, rows_on, ordered=True)
    _assert_equal_rows(rows_off, rows_on, ordered=True)
    _assert_balanced(on)
    _assert_balanced(off)


def test_adaptive_onoff_parity_agg_join():
    """The replan-eligible shape (both join inputs aggregated) stays
    bit-identical with adaptive fully disabled."""
    def q(s):
        big = s.create_dataframe(MIXED, num_partitions=3) \
            .group_by("k").agg(Column(Alias(Sum(ColumnRef("v")), "sv")),
                               Column(Alias(Count(ColumnRef("s")), "c")))
        dim = s.create_dataframe(DIM, num_partitions=2) \
            .group_by("k").agg(Column(Alias(Sum(ColumnRef("w")), "sw")))
        return big.join(dim, on="k", how="inner").collect()
    on, off, cpu = _sessions()
    rows_on, rows_off, rows_cpu = q(on), q(off), q(cpu)
    _assert_equal_rows(rows_cpu, rows_on)
    _assert_equal_rows(rows_off, rows_on)
    _assert_balanced(on)
    _assert_balanced(off)
    assert on.last_metrics.get("aqeBroadcastSwitches", 0) >= 1
    assert off.last_metrics.get("aqeBroadcastSwitches", 0) == 0
    # stats consumed by the replan were free: shuffle sync count identical
    assert on.last_metrics["shuffleSyncs"] <= off.last_metrics["shuffleSyncs"]


# -- coalescing economics ----------------------------------------------------


def test_coalesce_group_bound_and_dispatch_drop():
    """N shuffle partitions feed the join, at most
    ceil(total/targetBytes) coalesced tasks come out (no skew on uniform
    data), and the coalesced plan dispatches FEWER device programs than
    the uncoalesced one — with identical shuffle sync counts (the stats
    were already host-known)."""
    target = 2048
    n_in = 8
    confs = {"spark.sql.shuffle.partitions": n_in,
             "spark.sql.autoBroadcastJoinThreshold": -1,
             "spark.rapids.sql.tpu.adaptive.coalesce.targetBytes": target}

    def q(s):
        big = s.create_dataframe(FLAT, num_partitions=3)
        dim = s.create_dataframe(DIM, num_partitions=2)
        return big.join(dim, on="k", how="inner").collect()

    on = tpu_session(**dict(NO_COLLAPSE, **confs))
    off = tpu_session(**dict(NO_COLLAPSE, **confs, **ADAPTIVE_OFF))
    _assert_equal_rows(q(off), q(on))
    _assert_balanced(on)
    _assert_balanced(off)

    joins = [op for op in _metric_ops(on, "aqeCoalescedPartitions")
             if "aqeStatsBytes" in on.last_metrics[op]]
    assert joins, f"join did not pair-coalesce: {on.last_metrics}"
    ms = on.last_metrics[joins[0]]
    n_out = n_in - ms["aqeCoalescedPartitions"]
    total = ms["aqeStatsBytes"]
    assert total > 0
    assert 1 <= n_out <= math.ceil(total / target)

    # fewer downstream partitions -> fewer compiled-program dispatches
    assert on.last_metrics["dispatchCount"] < \
        off.last_metrics["dispatchCount"], \
        (on.last_metrics["dispatchCount"],
         off.last_metrics["dispatchCount"])
    # the statistics were free: both plans synced the device identically
    assert on.last_metrics["shuffleSyncs"] == \
        off.last_metrics["shuffleSyncs"]


# -- dynamic broadcast switch ------------------------------------------------


def _replan_join(s, how="inner"):
    big = s.create_dataframe(MIXED, num_partitions=3) \
        .group_by("k", "v").agg(Column(Alias(Count(ColumnRef("s")), "c")))
    dim = s.create_dataframe(DIM, num_partitions=2) \
        .group_by("k").agg(Column(Alias(Sum(ColumnRef("w")), "sw")))
    return big.join(dim, on="k", how=how)


def test_broadcast_switch_matches_static_broadcast_plan():
    """The runtime-switched join returns exactly what the compile-time
    broadcast plan (explicit hint) and the never-switched shuffled plan
    return, and elides the probe-side shuffle split."""
    switched = tpu_session(**NO_COLLAPSE)
    rows_sw = _replan_join(switched).collect()
    assert switched.last_metrics.get("aqeBroadcastSwitches", 0) >= 1, \
        switched.last_metrics
    assert _metric_ops(switched, "replannedBroadcast")
    assert _metric_ops(switched, "shuffleElided"), \
        "probe-side shuffle was not elided"

    static = tpu_session(**NO_COLLAPSE)
    big = static.create_dataframe(MIXED, num_partitions=3) \
        .group_by("k", "v").agg(Column(Alias(Count(ColumnRef("s")), "c")))
    dim = F.broadcast(
        static.create_dataframe(DIM, num_partitions=2)
        .group_by("k").agg(Column(Alias(Sum(ColumnRef("w")), "sw"))))
    rows_static = big.join(dim, on="k", how="inner").collect()

    never = tpu_session(**dict(
        NO_COLLAPSE, **{"spark.sql.autoBroadcastJoinThreshold": -1}))
    rows_never = _replan_join(never).collect()
    assert never.last_metrics.get("aqeBroadcastSwitches", 0) == 0

    _assert_equal_rows(rows_static, rows_sw)
    _assert_equal_rows(rows_never, rows_sw)
    for s in (switched, static, never):
        _assert_balanced(s)


def test_estimate_error_pct_recorded():
    """A shuffled join of scans (plan-time estimates known) records how
    far the static estimate was from the actual shuffled bytes."""
    s = tpu_session(**dict(
        NO_COLLAPSE, **{"spark.sql.autoBroadcastJoinThreshold": -1}))
    big = s.create_dataframe(FLAT, num_partitions=3)
    dim = s.create_dataframe(DIM, num_partitions=2)
    big.join(dim, on="k", how="inner").collect()
    assert "aqeEstimateErrorPct" in s.last_metrics
    assert s.last_metrics["aqeEstimateErrorPct"] >= 0.0
    assert _metric_ops(s, "aqeEstimateErrorPct"), s.last_metrics
    _assert_balanced(s)


# -- skew split --------------------------------------------------------------


HOT = {
    "k": (T.INT, [0] * 300 + [1, 2, 3]),
    "v": (T.LONG, list(range(303))),
}
HOT_DIM = {
    "k": (T.INT, [0, 1, 2, 3]),
    "w": (T.LONG, [7, 8, 9, 10]),
}


def test_skew_split_parity_one_hot_key():
    """One key holds ~99% of the rows: the hot partition is isolated,
    chunked per-source against the full build, and the answer matches
    both the CPU engine and the adaptive-off plan."""
    confs = dict(NO_COLLAPSE, **{
        "spark.sql.shuffle.partitions": 8,
        "spark.rapids.sql.tpu.adaptive.coalesce.targetBytes": 512,
        "spark.rapids.sql.tpu.adaptive.skew.thresholdBytes": 512,
        "spark.sql.autoBroadcastJoinThreshold": -1,
    })

    def q(s):
        big = s.create_dataframe(HOT, num_partitions=3)
        dim = s.create_dataframe(HOT_DIM, num_partitions=2)
        return big.join(dim, on="k", how="inner").collect()

    on = tpu_session(**confs)
    off = tpu_session(**dict(confs, **ADAPTIVE_OFF))
    cpu = cpu_session(**confs)
    rows_on, rows_off, rows_cpu = q(on), q(off), q(cpu)
    _assert_equal_rows(rows_cpu, rows_on)
    _assert_equal_rows(rows_off, rows_on)
    assert on.last_metrics.get("aqeSkewSplits", 0) >= 1, on.last_metrics
    chunk_ops = _metric_ops(on, "skewSplitChunks")
    assert chunk_ops, on.last_metrics
    assert sum(on.last_metrics[op]["skewSplitChunks"]
               for op in chunk_ops) >= 2
    assert off.last_metrics.get("aqeSkewSplits", 0) == 0
    _assert_balanced(on)
    _assert_balanced(off)


# -- device-lost replay through a switched join ------------------------------


@pytest.mark.parametrize("spec", [
    "dispatch:device_lost@1", "exchange:device_lost@1",
])
def test_device_lost_replay_through_switched_join(spec):
    """A device loss mid-query invalidates the generation-checked switch
    cache; the replay recomputes from lineage and the switched join
    still answers bit-identically."""
    clean = tpu_session(**NO_COLLAPSE)
    want = _replan_join(clean).collect()
    assert clean.last_metrics.get("aqeBroadcastSwitches", 0) >= 1

    s = tpu_session(**dict(
        NO_COLLAPSE, **{"spark.rapids.sql.tpu.faults.spec": spec}))
    got = _replan_join(s).collect()
    _assert_equal_rows(want, got)
    m = s.last_metrics
    assert m["deviceLostCount"] >= 1, m
    assert m.get("aqeBroadcastSwitches", 0) >= 1, m
    _assert_balanced(s)
