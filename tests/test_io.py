"""File IO tests: parquet/csv/orc write -> scan roundtrips through the
engine (GpuParquetScan / writer suites' pattern)."""

import os

import pytest

from spark_rapids_tpu import types as T

from compare import assert_tpu_cpu_equal, tpu_session

DATA = {
    "i": (T.INT, [1, 2, None, 4, 5, 6, 7, None]),
    "l": (T.LONG, [10, None, 30, 40, 50, 60, 70, 80]),
    "d": (T.DOUBLE, [0.5, 1.5, None, 3.5, 4.5, 5.5, 6.5, 7.5]),
    "s": (T.STRING, ["a", "bb", None, "dd", "", "ff", "gg", "hh"]),
    "b": (T.BOOLEAN, [True, False, None, True, False, True, None, False]),
}


@pytest.fixture
def pq_dir(tmp_path):
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=3)
    out = str(tmp_path / "data_pq")
    df.write_parquet(out)
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    return out


def test_parquet_roundtrip(pq_dir):
    def q(s):
        return s.read.parquet(pq_dir).order_by("i", "l")
    assert_tpu_cpu_equal(q, ignore_order=False)


def test_parquet_scan_filter_agg(pq_dir):
    from spark_rapids_tpu import functions as F

    def q(s):
        df = s.read.parquet(pq_dir)
        return df.filter(df["i"].is_not_null()) \
                 .group_by("b").agg(F.sum("l").alias("sum_l"))
    assert_tpu_cpu_equal(q)


def test_csv_roundtrip(tmp_path):
    s = tpu_session()
    data = {k: v for k, v in DATA.items() if k != "b"}
    df = s.create_dataframe(data, num_partitions=2)
    out = str(tmp_path / "data_csv")
    df.write_csv(out)

    def q(s2):
        return s2.read.csv(out).order_by("i", "l")
    assert_tpu_cpu_equal(q, ignore_order=False, approx=True)


def test_orc_roundtrip(tmp_path):
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    out = str(tmp_path / "data_orc")
    df.write_orc(out)

    def q(s2):
        return s2.read.orc(out).order_by("i", "l")
    assert_tpu_cpu_equal(q, ignore_order=False)


def test_write_modes(tmp_path):
    s = tpu_session()
    df = s.create_dataframe(DATA)
    out = str(tmp_path / "m")
    df.write_parquet(out)
    with pytest.raises(FileExistsError):
        df.write_parquet(out, mode="error")
    df.write_parquet(out, mode="overwrite")
    df.write_parquet(out, mode="ignore")
    got = s.read.parquet(out).count()
    assert got == 8


# ---------------------------------------------------------------------------
# Predicate pushdown + Hive partitioned reads
# ---------------------------------------------------------------------------


def _scan_metrics(sess):
    for op, ms in sess.last_metrics.items():
        if "FileScan" in op and "rowGroupsTotal" in ms:
            return ms
    return {}


def test_parquet_row_group_pushdown_skips_groups(tmp_path):
    """A selective filter over a sorted column must decode fewer row groups
    than the file holds (GpuParquetScan.scala:217-281 filterBlocks role)."""
    import numpy as np
    s = tpu_session()
    s.conf.set("spark.rapids.sql.format.parquet.multiThreadedRead.numThreads", 2)
    n = 50_000
    df = s.create_dataframe({
        "k": (T.LONG, list(range(n))),
        "v": (T.DOUBLE, (np.arange(n) * 0.5).tolist()),
    })
    out = str(tmp_path / "sorted_pq")
    df.write_parquet(out)
    # force small row groups by rewriting with pyarrow
    import pyarrow.parquet as pq
    import pyarrow as pa
    files = [f for f in os.listdir(out) if f.endswith(".parquet")]
    tables = [pq.read_table(os.path.join(out, f)) for f in files]
    big = pa.concat_tables(tables)
    for f in files:
        os.remove(os.path.join(out, f))
    pq.write_table(big, os.path.join(out, "part-00000.parquet"),
                   row_group_size=5_000)

    sel = s.read.parquet(out)
    got = sel.filter(sel["k"] < 4_000).collect()
    assert len(got) == 4_000
    ms = _scan_metrics(s)
    assert ms.get("rowGroupsTotal", 0) == 10
    assert ms.get("rowGroupsRead", 0) <= 1, ms

    # unfiltered read decodes everything
    s2 = tpu_session()
    assert len(s2.read.parquet(out).collect()) == n
    ms2 = _scan_metrics(s2)
    assert ms2.get("rowGroupsRead") == ms2.get("rowGroupsTotal") == 10


def test_parquet_pushdown_correctness_vs_cpu(tmp_path):
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    out = str(tmp_path / "pq_pd")
    df.write_parquet(out)

    def q(sess):
        d = sess.read.parquet(out)
        return d.filter((d["i"] > 2) & d["l"].is_not_null())

    assert_tpu_cpu_equal(q)


def test_partitioned_write_read_roundtrip(tmp_path):
    """partition_by write -> read recovers the partition key column
    (ColumnarPartitionReaderWithPartitionValues role)."""
    s = tpu_session()
    data = {
        "k": (T.STRING, ["x", "y", "x", "z", None, "y"]),
        "n": (T.LONG, [1, 2, 3, 4, 5, 6]),
        "v": (T.DOUBLE, [0.1, 0.2, 0.3, 0.4, 0.5, 0.6]),
    }
    out = str(tmp_path / "part_pq")
    s.create_dataframe(data).write_parquet(out, partition_by=["k"])
    back = s.read.parquet(out)
    assert set(back.schema.names) == {"n", "v", "k"}
    rows = sorted(back.select("n", "k").collect())
    assert rows == [(1, "x"), (2, "y"), (3, "x"), (4, "z"), (5, None),
                    (6, "y")]


def test_partitioned_numeric_key_typed(tmp_path):
    s = tpu_session()
    data = {"yr": (T.LONG, [2020, 2021, 2020]),
            "v": (T.LONG, [1, 2, 3])}
    out = str(tmp_path / "part_num")
    s.create_dataframe(data).write_parquet(out, partition_by=["yr"])
    back = s.read.parquet(out)
    f = {x.name: x.dtype for x in back.schema.fields}
    assert f["yr"] == T.LONG
    assert sorted(back.collect()) == [(1, 2020), (2, 2021), (3, 2020)]


def test_partition_pruning_skips_files(tmp_path):
    s = tpu_session()
    data = {"k": (T.STRING, ["a", "b", "c", "a"]),
            "v": (T.LONG, [1, 2, 3, 4])}
    out = str(tmp_path / "part_prune")
    s.create_dataframe(data).write_parquet(out, partition_by=["k"])
    d = s.read.parquet(out)
    got = d.filter(d["k"] == "a").collect()
    assert sorted(got) == [(1, "a"), (4, "a")]
    # physical plan pruned to only the k=a file
    plan = s.last_physical_plan.tree_string()
    assert "1 files" in plan, plan


def test_orc_stripe_pushdown(tmp_path):
    """ORC stripe skipping: selective predicate decodes fewer stripes
    (OrcFilters/SearchArgument role)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.orc as orc
    out = str(tmp_path / "sorted_orc")
    os.makedirs(out)
    n = 50_000
    tb = pa.table({"k": np.arange(n, dtype=np.int64),
                   "v": np.arange(n, dtype=np.float64) * 0.5})
    orc.write_table(tb, os.path.join(out, "part-0.orc"),
                    stripe_size=64 * 1024)
    s = tpu_session()
    d = s.read.orc(out)
    nst = orc.ORCFile(os.path.join(out, "part-0.orc")).nstripes
    assert nst > 2
    got = d.filter(d["k"] < 1000).collect()
    assert len(got) == 1000
    ms = _scan_metrics(s)
    assert ms.get("rowGroupsTotal") == nst
    assert 0 < ms.get("rowGroupsRead", 0) < nst, ms


def test_orc_pushdown_correctness(tmp_path):
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    out = str(tmp_path / "orc_pd")
    df.write_orc(out)

    def q(sess):
        d = sess.read.orc(out)
        return d.filter((d["i"] > 2) & d["l"].is_not_null())

    assert_tpu_cpu_equal(q)


def test_orc_pushdown_keeps_nan_stripes(tmp_path):
    """NaN in a stripe must not poison the computed min/max into skipping
    rows that genuinely match (plain min() would propagate NaN and fail
    every range test)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.orc as orc
    out = str(tmp_path / "nan_orc")
    os.makedirs(out)
    n = 20_000
    v = np.linspace(0.0, 1.0, n)
    v[::97] = np.nan  # NaN sprinkled through every stripe
    tb = pa.table({"v": v, "k": np.arange(n, dtype=np.int64)})
    orc.write_table(tb, os.path.join(out, "p.orc"), stripe_size=64 * 1024)
    s = tpu_session()
    d = s.read.orc(out)
    got = d.filter(d["v"] < 0.5).collect()
    expect = sum(1 for x in v if x == x and x < 0.5)
    assert len(got) == expect
