"""File IO tests: parquet/csv/orc write -> scan roundtrips through the
engine (GpuParquetScan / writer suites' pattern)."""

import os

import pytest

from spark_rapids_tpu import types as T

from compare import assert_tpu_cpu_equal, tpu_session

DATA = {
    "i": (T.INT, [1, 2, None, 4, 5, 6, 7, None]),
    "l": (T.LONG, [10, None, 30, 40, 50, 60, 70, 80]),
    "d": (T.DOUBLE, [0.5, 1.5, None, 3.5, 4.5, 5.5, 6.5, 7.5]),
    "s": (T.STRING, ["a", "bb", None, "dd", "", "ff", "gg", "hh"]),
    "b": (T.BOOLEAN, [True, False, None, True, False, True, None, False]),
}


@pytest.fixture
def pq_dir(tmp_path):
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=3)
    out = str(tmp_path / "data_pq")
    df.write_parquet(out)
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    return out


def test_parquet_roundtrip(pq_dir):
    def q(s):
        return s.read.parquet(pq_dir).order_by("i", "l")
    assert_tpu_cpu_equal(q, ignore_order=False)


def test_parquet_scan_filter_agg(pq_dir):
    from spark_rapids_tpu import functions as F

    def q(s):
        df = s.read.parquet(pq_dir)
        return df.filter(df["i"].is_not_null()) \
                 .group_by("b").agg(F.sum("l").alias("sum_l"))
    assert_tpu_cpu_equal(q)


def test_csv_roundtrip(tmp_path):
    s = tpu_session()
    data = {k: v for k, v in DATA.items() if k != "b"}
    df = s.create_dataframe(data, num_partitions=2)
    out = str(tmp_path / "data_csv")
    df.write_csv(out)

    def q(s2):
        return s2.read.csv(out).order_by("i", "l")
    assert_tpu_cpu_equal(q, ignore_order=False, approx=True)


def test_orc_roundtrip(tmp_path):
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    out = str(tmp_path / "data_orc")
    df.write_orc(out)

    def q(s2):
        return s2.read.orc(out).order_by("i", "l")
    assert_tpu_cpu_equal(q, ignore_order=False)


def test_write_modes(tmp_path):
    s = tpu_session()
    df = s.create_dataframe(DATA)
    out = str(tmp_path / "m")
    df.write_parquet(out)
    with pytest.raises(FileExistsError):
        df.write_parquet(out, mode="error")
    df.write_parquet(out, mode="overwrite")
    df.write_parquet(out, mode="ignore")
    got = s.read.parquet(out).count()
    assert got == 8
