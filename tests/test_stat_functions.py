"""DataFrameStatFunctions surface: crosstab, approx_quantile,
freq_items, sample_by."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T

from compare import assert_tpu_cpu_equal, tpu_session

DATA = {"k": (T.STRING, ["a", "a", "a", "b", "b", "c"] * 10),
        "p": (T.STRING, ["x", "y", "x", "x", None, "y"] * 10),
        "v": (T.DOUBLE, [float(i) for i in range(60)])}


def test_crosstab():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    out = df.crosstab("k", "p").order_by("k_p")
    assert out.columns == ["k_p", "null", "x", "y"]
    rows = {r[0]: r[1:] for r in out.collect()}
    assert rows["a"] == (0, 20, 10)
    assert rows["b"] == (10, 10, 0)
    assert rows["c"] == (0, 0, 10)

    def build(s2):
        d = s2.create_dataframe(DATA, num_partitions=3)
        return d.crosstab("k", "p").order_by("k_p")

    assert_tpu_cpu_equal(build, ignore_order=False)


def test_approx_quantile_exact():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=3)
    qs = df.approx_quantile("v", [0.0, 0.5, 1.0])
    vals = np.arange(60.0)
    assert qs[0] == 0.0 and qs[2] == 59.0
    assert qs[1] == pytest.approx(float(np.percentile(vals, 50)))


def test_freq_items():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    out = df.freq_items(["k"], support=0.4).collect()
    # only 'a' (30/60) crosses 40% -- wait: 30/60 = 0.5 > 0.4; b = 20/60
    assert out[0][0] == ["a"]
    out = df.freq_items(["k"], support=0.1).collect()
    assert sorted(out[0][0]) == ["a", "b", "c"]


def test_sample_by():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    out = df.sample_by("k", {"a": 1.0, "b": 0.0}).collect()
    ks = [r[0] for r in out]
    assert set(ks) == {"a"} and len(ks) == 30  # all a's, no b's, c dropped
    with pytest.raises(ValueError):
        df.sample_by("k", {"a": 1.5})

    # rand() draws depend on the physical plan (as in Spark), so no
    # cross-engine row equality; assert the strata guarantees instead
    out = df.sample_by("k", {"a": 0.5, "c": 1.0}, seed=7).collect()
    ks = [r[0] for r in out]
    assert "b" not in ks                     # absent keys dropped
    assert ks.count("c") == 10               # fraction 1.0 keeps all
    assert 0 <= ks.count("a") <= 30          # fraction 0.5 subset
    # deterministic per engine+seed
    again = df.sample_by("k", {"a": 0.5, "c": 1.0}, seed=7).collect()
    assert out == again


def test_stat_functions_edge_cases():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=1)
    assert df.approx_quantile("v", []) == []
    assert df.sample_by("k", {}).collect() == []
    d2 = s.create_dataframe(
        {"k": (T.STRING, ["a", None, "b"]),
         "p": (T.STRING, ["x", "x", None])}, num_partitions=1)
    rows = d2.crosstab("k", "p").order_by("k_p").collect()
    keys = [r[0] for r in rows]
    assert "null" in keys  # NULL key rendered as the string "null"
