"""Array-type + explode tests (GpuGenerateExec / nested-type envelope v1)."""

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T

from compare import assert_tpu_cpu_equal, tpu_session

ARR = T.ArrayType(T.LONG)
DATA = {
    "k": (T.STRING, ["a", "b", "c", "d", "e"]),
    "arr": (ARR, [[1, 2, 3], [], [4], None, [5, 6]]),
    "v": (T.LONG, [10, 20, 30, 40, 50]),
}


def test_array_roundtrip():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    rows = df.select("k", "arr").collect()
    got = dict(rows)
    assert got["a"] == [1, 2, 3] and got["b"] == [] and got["d"] is None


def test_explode_on_tpu():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    out = df.explode("arr", alias="e")
    rows = sorted(out.collect())
    assert rows == sorted([
        ("a", 10, 1), ("a", 10, 2), ("a", 10, 3), ("c", 30, 4),
        ("e", 50, 5), ("e", 50, 6)])
    assert "TpuGenerate" in s.last_physical_plan.tree_string()


def test_explode_vs_cpu_oracle():
    assert_tpu_cpu_equal(
        lambda s: s.create_dataframe(DATA, num_partitions=3)
        .explode("arr", alias="e").filter(F.col("e") > 1))


def test_posexplode():
    assert_tpu_cpu_equal(
        lambda s: s.create_dataframe(DATA).explode(
            "arr", alias="e", pos=True))


def test_explode_outer_falls_back():
    def q(s):
        return s.create_dataframe(DATA).explode("arr", alias="e",
                                                outer=True)
    assert_tpu_cpu_equal(q, expect_fallback="Generate")
    s = tpu_session()
    rows = q(s).collect()
    # 'b' (empty) and 'd' (NULL array) each keep one NULL-element row
    assert ("b", 20, None) in rows and ("d", 40, None) in rows


def test_create_array_and_explode():
    def q(s):
        df = s.create_dataframe({"x": (T.LONG, [1, 2]),
                                 "y": (T.LONG, [10, 20])})
        return df.with_column("a", F.array("x", "y")).explode("a", "e")
    assert_tpu_cpu_equal(q)


def test_infer_list_dtype():
    s = tpu_session()
    df = s.create_dataframe({"a": [[1, 2], [3]], "n": [1, 2]})
    assert df.schema.field("a").dtype == T.ArrayType(T.LONG)
    assert sorted(df.explode("a", "e").collect()) == \
        [(1, 1), (1, 2), (2, 3)]


def test_groupby_on_exploded():
    """Explode feeding a TPU aggregation (arrays gone from the schema by
    then, so the agg stays on device)."""
    def q(s):
        df = s.create_dataframe(DATA, num_partitions=2)
        return df.explode("arr", "e").group_by("k").agg(
            F.sum(F.col("e")).alias("s"), F.count(F.col("e")).alias("c"))
    assert_tpu_cpu_equal(q)


def test_array_column_blocks_tpu_sort():
    s = tpu_session()
    df = s.create_dataframe(DATA)
    df.order_by("k").collect()
    assert "cannot run on TPU" in s.last_explain \
        and "array columns" in s.last_explain


def test_get_item_and_size():
    def q(s):
        df = s.create_dataframe(DATA, num_partitions=2)
        return df.select(
            "k",
            F.get_item("arr", 0).alias("first"),
            F.get_item("arr", 2).alias("third"),
            F.size("arr").alias("n"))
    assert_tpu_cpu_equal(q)
    s = tpu_session()
    rows = q(s).collect()
    got = {r[0]: r[1:] for r in rows}
    assert got["a"] == (1, 3, 3)
    assert got["b"] == (None, None, 0)
    assert got["d"] == (None, None, None)


def test_get_item_negative_ordinal_is_null():
    """Spark semantics: negative ordinals are out of range -> NULL (not
    python tail indexing) — on both engines, including NULL-array rows."""
    def q(s):
        df = s.create_dataframe(DATA, num_partitions=2)
        return df.select("k", F.get_item("arr", -1).alias("m"))
    assert_tpu_cpu_equal(q)
    s = tpu_session()
    assert all(r[1] is None for r in q(s).collect())


class TestArrayFunctions:
    DATA = {"g": (T.STRING, ["a", "b", "c", "d"]),
            "arr": (T.ArrayType(T.INT),
                    [[1, 5, 3], [7], [], None])}

    def test_array_contains_min_max(self):
        def build(s):
            df = s.create_dataframe(self.DATA, num_partitions=2)
            return df.select(
                df["g"],
                F.array_contains(df["arr"], 5).alias("has5"),
                F.array_min("arr").alias("mn"),
                F.array_max("arr").alias("mx")).order_by("g")

        assert_tpu_cpu_equal(build, ignore_order=False)
        from compare import tpu_session
        s = tpu_session()
        df = s.create_dataframe(self.DATA, num_partitions=1)
        rows = df.select(
            F.array_contains(df["arr"], 5).alias("h"),
            F.array_min("arr").alias("mn"),
            F.array_max("arr").alias("mx")).collect()
        assert rows[0] == (True, 1, 5)
        assert rows[1] == (False, 7, 7)
        assert rows[2] == (False, None, None)   # empty array
        assert rows[3] == (None, None, None)    # NULL array

    def test_array_functions_sql(self):
        def build(s):
            s.register_view("t", s.create_dataframe(self.DATA,
                                                    num_partitions=2))
            return s.sql(
                "SELECT g, array_contains(arr, 3) AS h, "
                "array_min(arr) AS mn, array_max(arr) AS mx "
                "FROM t ORDER BY g")

        assert_tpu_cpu_equal(build, ignore_order=False)

    def test_array_contains_rejects_null_needle(self):
        from compare import tpu_session
        s = tpu_session()
        df = s.create_dataframe(self.DATA, num_partitions=1)
        with pytest.raises(ValueError):
            F.array_contains(df["arr"], None)

    def test_array_min_max_nan_ordering(self):
        data = {"arr": (T.ArrayType(T.DOUBLE),
                        [[1.0, float("nan")], [float("nan")],
                         [2.0, 3.0]])}

        def build(s):
            df = s.create_dataframe(data, num_partitions=2)
            return df.select(F.array_min("arr").alias("mn"),
                             F.array_max("arr").alias("mx"))

        assert_tpu_cpu_equal(build, approx=True, ignore_order=False)
        from compare import tpu_session
        s = tpu_session()
        rows = s.create_dataframe(data, num_partitions=1).select(
            F.array_min("arr").alias("mn"),
            F.array_max("arr").alias("mx")).collect()
        import math
        # Spark: NaN is the largest value
        assert rows[0][0] == 1.0 and math.isnan(rows[0][1])
        assert math.isnan(rows[1][0]) and math.isnan(rows[1][1])
        assert rows[2] == (2.0, 3.0)

    def test_array_contains_type_mismatch_rejected(self):
        from compare import tpu_session
        s = tpu_session()
        df = s.create_dataframe(self.DATA, num_partitions=1)
        with pytest.raises(TypeError):
            df.select(F.array_contains(df["arr"], 2.5).alias("h")) \
                .collect()

    def test_sort_array_and_position(self):
        data = {"arr": (T.ArrayType(T.INT),
                        [[3, 1, 2], [5], [], None, [9, 9, 1]])}

        def build(s):
            df = s.create_dataframe(data, num_partitions=2)
            return df.select(
                F.sort_array("arr").alias("sa"),
                F.sort_array("arr", asc=False).alias("sd"),
                F.array_position(df["arr"], 9).alias("p9"))

        assert_tpu_cpu_equal(build, ignore_order=False)
        from compare import tpu_session
        s = tpu_session()
        rows = s.create_dataframe(data, num_partitions=1).select(
            F.sort_array("arr").alias("sa"),
            F.sort_array("arr", asc=False).alias("sd"),
            F.array_position(F.col("arr"), 1).alias("p1")).collect()
        assert rows[0] == ([1, 2, 3], [3, 2, 1], 2)
        assert rows[1] == ([5], [5], 0)
        assert rows[2] == ([], [], 0)
        assert rows[3] == (None, None, None)
        assert rows[4][0] == [1, 9, 9] and rows[4][2] == 3

    def test_sort_array_nan_and_sql(self):
        data = {"arr": (T.ArrayType(T.DOUBLE),
                        [[2.0, float("nan"), 1.0]])}

        def build(s):
            s.register_view("t", s.create_dataframe(data,
                                                    num_partitions=1))
            return s.sql("SELECT sort_array(arr) AS sa, "
                         "sort_array(arr, false) AS sd, "
                         "array_position(arr, 2.0) AS p FROM t")

        assert_tpu_cpu_equal(build, approx=True, ignore_order=False)
        from compare import tpu_session
        s = tpu_session()
        s.register_view("t", s.create_dataframe(data, num_partitions=1))
        row = s.sql("SELECT sort_array(arr) AS sa FROM t").collect()[0]
        import math
        assert row[0][0] == 1.0 and row[0][1] == 2.0 \
            and math.isnan(row[0][2])  # NaN sorts largest

    def test_sort_array_nan_vs_inf_and_int_extremes(self):
        data = {"f": (T.ArrayType(T.DOUBLE),
                      [[float("nan"), float("inf"), 1.0]]),
                "l": (T.ArrayType(T.LONG),
                      [[-9223372036854775808, 0, 5]])}

        def build(s):
            df = s.create_dataframe(data, num_partitions=1)
            return df.select(
                F.sort_array("f").alias("fa"),
                F.sort_array("f", asc=False).alias("fd"),
                F.sort_array("l", asc=False).alias("ld"))

        assert_tpu_cpu_equal(build, approx=True, ignore_order=False)
        from compare import tpu_session
        import math
        s = tpu_session()
        row = s.create_dataframe(data, num_partitions=1).select(
            F.sort_array("f").alias("fa"),
            F.sort_array("f", asc=False).alias("fd"),
            F.sort_array("l", asc=False).alias("ld")).collect()[0]
        fa, fd, ld = row
        assert fa[0] == 1.0 and fa[1] == float("inf") \
            and math.isnan(fa[2])            # NaN strictly after +inf
        assert math.isnan(fd[0]) and fd[1] == float("inf")
        assert ld == [5, 0, -9223372036854775808]  # no INT64_MIN wrap

    def test_sql_explode_in_select(self):
        data = {"g": (T.STRING, ["a", "b", "c"]),
                "arr": (T.ArrayType(T.INT), [[1, 2], [3], []])}

        def build(s):
            s.register_view("t", s.create_dataframe(data,
                                                    num_partitions=2))
            return s.sql("SELECT g, explode(arr) AS e FROM t "
                         "ORDER BY g, e")

        assert_tpu_cpu_equal(build, ignore_order=False)
        from compare import tpu_session
        s = tpu_session()
        s.register_view("t", s.create_dataframe(data, num_partitions=1))
        rows = s.sql("SELECT g, explode(arr) AS e FROM t "
                     "ORDER BY g, e").collect()
        assert rows == [("a", 1), ("a", 2), ("b", 3)]  # empty drops
        rows = s.sql("SELECT g, pos, e FROM "
                     "(SELECT g, posexplode(arr) AS e FROM t) "
                     "ORDER BY g, pos").collect()
        assert rows == [("a", 0, 1), ("a", 1, 2), ("b", 0, 3)]

    def test_sql_explode_restrictions(self):
        from compare import tpu_session
        s = tpu_session()
        s.register_view("t", s.create_dataframe(
            {"a": (T.ArrayType(T.INT), [[1]]),
             "b": (T.ArrayType(T.INT), [[2]])}, num_partitions=1))
        with pytest.raises(SyntaxError):
            s.sql("SELECT explode(a) AS x, explode(b) AS y FROM t")

    def test_sql_explode_with_where_and_guards(self):
        data = {"g": (T.STRING, ["a", "b"]),
                "arr": (T.ArrayType(T.INT), [[1, 2], [3]])}

        def build(s):
            s.register_view("t", s.create_dataframe(data,
                                                    num_partitions=1))
            # WHERE references the array column the explode consumes
            return s.sql("SELECT g, explode(arr) AS e FROM t "
                         "WHERE size(arr) > 1 ORDER BY g, e")

        assert_tpu_cpu_equal(build, ignore_order=False)
        from compare import tpu_session
        s = tpu_session()
        s.register_view("t", s.create_dataframe(data, num_partitions=1))
        rows = s.sql("SELECT g, explode(arr) AS e FROM t "
                     "WHERE size(arr) > 1 ORDER BY g, e").collect()
        assert rows == [("a", 1), ("a", 2)]
        with pytest.raises(SyntaxError):
            s.sql("SELECT explode(arr) + 1 AS x FROM t")
        with pytest.raises(SyntaxError):
            s.sql("SELECT *, explode(arr) AS e FROM t")
        with pytest.raises(SyntaxError):
            s.sql("SELECT g FROM t WHERE explode(arr) > 1")
