"""Scan v2 (io.scan_v2) tests: bit parity with the v1 scan across formats
and features (dictionary strings, nulls, partition-value columns, late
materialization), read-ahead semantics, fault replay through the retry
ladder, and clean resource accounting after a streamed scan."""

import os
import threading

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T

from compare import tpu_session

DATA = {
    "i": (T.INT, [1, 2, None, 4, 5, 6, 7, None] * 25),
    "l": (T.LONG, [10, None, 30, 40, 50, 60, 70, 80] * 25),
    "d": (T.DOUBLE, [0.5, 1.5, None, 3.5, 4.5, 5.5, 6.5, 7.5] * 25),
    # low-cardinality strings with nulls and empties: the dictionary case
    "s": (T.STRING, ["aa", "bb", None, "bb", "", "cc", "aa", "cc"] * 25),
}


def _v1_session(**confs):
    return tpu_session(**{"spark.rapids.sql.tpu.scan.v2.enabled": False,
                          **confs})


def _v2_session(**confs):
    return tpu_session(**{"spark.rapids.sql.tpu.scan.v2.enabled": True,
                          **confs})


def _write_multi_row_group_parquet(tmp_path, name="pq", rows_per_group=40):
    """Engine-written parquet rewritten into ONE file with small row
    groups, so chunk-granular behavior is actually exercised."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    s = _v1_session()
    out = str(tmp_path / name)
    s.create_dataframe(DATA, num_partitions=2).write_parquet(out)
    files = [f for f in os.listdir(out) if f.endswith(".parquet")]
    big = pa.concat_tables(
        [pq.read_table(os.path.join(out, f)) for f in files])
    for f in files:
        os.remove(os.path.join(out, f))
    pq.write_table(big, os.path.join(out, "part-00000.parquet"),
                   row_group_size=rows_per_group)
    return out


def _rows(session, build):
    return sorted(build(session).collect(),
                  key=lambda r: tuple((v is None, str(v)) for v in r))


def _assert_v1_v2_parity(build, **confs):
    want = _rows(_v1_session(**confs), build)
    got = _rows(_v2_session(**confs), build)
    assert got == want, (got[:5], want[:5])
    return got


# -- format parity -----------------------------------------------------------


def test_parquet_parity_with_dict_strings_and_nulls(tmp_path):
    out = _write_multi_row_group_parquet(tmp_path)
    _assert_v1_v2_parity(lambda s: s.read.parquet(out))


def test_parquet_parity_projection_and_filter(tmp_path):
    out = _write_multi_row_group_parquet(tmp_path)

    def q(s):
        df = s.read.parquet(out)
        return df.filter(df["i"] < 5).select("s", "l")
    _assert_v1_v2_parity(q)


def test_orc_parity(tmp_path):
    s = _v1_session()
    out = str(tmp_path / "orc")
    s.create_dataframe(DATA, num_partitions=2).write_orc(out)
    _assert_v1_v2_parity(lambda s2: s2.read.orc(out))


def test_csv_parity(tmp_path):
    s = _v1_session()
    data = {k: v for k, v in DATA.items() if k != "s"}
    out = str(tmp_path / "csv")
    s.create_dataframe(data, num_partitions=2).write_csv(out)
    _assert_v1_v2_parity(lambda s2: s2.read.csv(out))


def test_partition_value_columns_parity(tmp_path):
    """Hive-partitioned read: partition columns (including a string one,
    which v2 dict-encodes) must round-trip identically."""
    s = _v1_session()
    out = str(tmp_path / "part_pq")
    data = {
        "k": (T.INT, [0, 0, 1, 1, 2, 2, 0, 1]),
        "grp": (T.STRING, ["x", "x", "y", "y", "z", "z", "x", "y"]),
        "v": (T.DOUBLE, [1.0, 2.0, 3.0, None, 5.0, 6.0, 7.0, 8.0]),
    }
    s.create_dataframe(data).write_parquet(out, partition_by=["grp"])

    def q(s2):
        df = s2.read.parquet(out)
        return df.group_by("grp").agg(F.count("k").alias("c"),
                                      F.sum("v").alias("sv"))
    _assert_v1_v2_parity(q)


# -- dict-encoded device paths -----------------------------------------------


def test_string_filter_eq_parity(tmp_path):
    out = _write_multi_row_group_parquet(tmp_path)

    def q(s):
        df = s.read.parquet(out)
        return df.filter(df["s"] == "bb").select("i", "s")
    rows = _assert_v1_v2_parity(q)
    assert len(rows) == 50


def test_string_groupby_keys_parity(tmp_path):
    out = _write_multi_row_group_parquet(tmp_path)

    def q(s):
        df = s.read.parquet(out)
        return df.group_by("s").agg(F.count("i").alias("c"),
                                    F.sum("l").alias("sl"))
    _assert_v1_v2_parity(q)


def test_scan_dict_metrics_recorded(tmp_path):
    out = _write_multi_row_group_parquet(tmp_path)
    s = _v2_session()
    df = s.read.parquet(out)
    df.group_by("s").agg(F.count("i").alias("c")).collect()
    m = s.last_metrics
    assert m.get("scanBytesDecoded", 0) > 0, m
    assert m.get("scanDecodeWallNs", 0) > 0, m
    assert m.get("scanDictColumns", 0) > 0, m


def test_dict_disabled_still_parity(tmp_path):
    out = _write_multi_row_group_parquet(tmp_path)

    def q(s):
        df = s.read.parquet(out)
        return df.group_by("s").agg(F.count("i").alias("c"))
    _assert_v1_v2_parity(
        q, **{"spark.rapids.sql.tpu.scan.dictEncoding.enabled": False})


# -- late materialization ----------------------------------------------------


def _needle_parquet(tmp_path):
    """Unsorted tag column whose per-chunk min/max brackets the needle, so
    row-group statistics cannot skip — only the exact late-mat probe can."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.RandomState(3)
    n = 4_000
    tag = (rng.randint(-500, 500, n) * 2).astype(np.int64)
    tag[2 * 500 + 11] = 501  # odd needle in chunk 2 of 8
    out = str(tmp_path / "needle_pq")
    os.makedirs(out)
    pq.write_table(pa.table({
        "tag": pa.array(tag),
        "v": pa.array(rng.rand(n).round(4)),
        "s": pa.array(np.array(["s%d" % (i % 7) for i in range(n)],
                               dtype=object)),
    }), os.path.join(out, "part-00000.parquet"), row_group_size=500)
    return out


def test_late_mat_selective_predicate_skips_chunks(tmp_path):
    out = _needle_parquet(tmp_path)

    def q(s):
        df = s.read.parquet(out)
        return df.filter(df["tag"] == 501)
    rows = _assert_v1_v2_parity(q)
    assert len(rows) == 1
    s = _v2_session()
    df = s.read.parquet(out)
    assert len(df.filter(df["tag"] == 501).collect()) == 1
    m = s.last_metrics
    assert m.get("scanChunksSkipped", 0) == 7, m


def test_late_mat_select_all_predicate_skips_nothing(tmp_path):
    out = _needle_parquet(tmp_path)

    def q(s):
        df = s.read.parquet(out)
        return df.filter(df["tag"] > -10_000)
    rows = _assert_v1_v2_parity(q)
    assert len(rows) == 4_000
    s = _v2_session()
    df = s.read.parquet(out)
    assert len(df.filter(df["tag"] > -10_000).collect()) == 4_000
    assert s.last_metrics.get("scanChunksSkipped", 0) == 0


def test_late_mat_disabled_parity(tmp_path):
    out = _needle_parquet(tmp_path)

    def q(s):
        df = s.read.parquet(out)
        return df.filter(df["tag"] == 501)
    _assert_v1_v2_parity(
        q, **{"spark.rapids.sql.tpu.scan.lateMaterialization.enabled":
              False})
    s = _v2_session(**{
        "spark.rapids.sql.tpu.scan.lateMaterialization.enabled": False})
    df = s.read.parquet(out)
    assert len(df.filter(df["tag"] == 501).collect()) == 1
    assert s.last_metrics.get("scanChunksSkipped", 0) == 0


def test_orc_non_projected_predicate_column_skips(tmp_path):
    """Satellite regression: an ORC predicate on a column NOT in the
    projection must still drive stripe skipping."""
    s = _v1_session()
    n = 2_000
    data = {
        "k": (T.LONG, list(range(n))),
        "v": (T.DOUBLE, [float(i) * 0.5 for i in range(n)]),
    }
    out = str(tmp_path / "orc_sorted")
    s.create_dataframe(data, num_partitions=1).write_orc(out)

    def q(s2):
        d = s2.read.orc(out)
        return d.filter(d["k"] < 10).select("v")
    rows = _assert_v1_v2_parity(q)
    assert len(rows) == 10


# -- read-ahead semantics ----------------------------------------------------


@pytest.mark.parametrize("depth", [0, 1, 3, 16])
def test_readahead_depth_values_all_equal(tmp_path, depth):
    """Any depth (0 clamps to 1) yields the same deterministic rows in the
    same submission order."""
    out = _write_multi_row_group_parquet(tmp_path)
    want = _rows(_v2_session(), lambda s: s.read.parquet(out))
    s = _v2_session(**{"spark.rapids.sql.tpu.scan.readAhead.depth": depth})
    got = _rows(s, lambda s2: s2.read.parquet(out))
    assert got == want


def test_readahead_window_is_bounded(tmp_path, monkeypatch):
    """No more than `depth` decode futures may be in flight at once."""
    import spark_rapids_tpu.io.scan_v2 as sv2
    out = _write_multi_row_group_parquet(tmp_path, rows_per_group=20)
    live = {"now": 0, "max": 0}
    lock = threading.Lock()
    orig = sv2.FileScanV2Exec._decode_parquet_chunk

    def counting(self, *a, **kw):
        with lock:
            live["now"] += 1
            live["max"] = max(live["max"], live["now"])
        try:
            return orig(self, *a, **kw)
        finally:
            with lock:
                live["now"] -= 1
    monkeypatch.setattr(sv2.FileScanV2Exec, "_decode_parquet_chunk",
                        counting)
    s = _v2_session(**{"spark.rapids.sql.tpu.scan.readAhead.depth": 2})
    assert len(s.read.parquet(out).collect()) == 200
    assert 1 <= live["max"] <= 2, live


# -- faults + resource accounting --------------------------------------------


def test_scan_oom_fault_replays_through_retry_ladder(tmp_path):
    out = _write_multi_row_group_parquet(tmp_path)

    def q(s):
        df = s.read.parquet(out)
        return df.group_by("s").agg(F.count("i").alias("c"))
    want = _rows(_v2_session(), q)
    s = _v2_session(**{"spark.rapids.sql.tpu.faults.spec": "scan:oom@2"})
    got = _rows(s, q)
    assert got == want
    m = s.last_metrics
    assert m["faultsInjected"] >= 1, m
    assert m["retryCount"] >= 1, m


def test_streamed_scan_leaves_clean_accounting(tmp_path):
    out = _write_multi_row_group_parquet(tmp_path)
    s = _v2_session()
    df = s.read.parquet(out)
    rows = df.group_by("s").agg(F.sum("l").alias("sl")).collect()
    assert rows
    assert s.runtime.semaphore.held_depth() == 0
    cat = s.runtime.catalog
    assert cat.device_bytes_in_use() == 0, cat.metrics


def test_decode_pool_is_shared_and_bounded(tmp_path):
    """Satellite regression: repeated scans must reuse ONE process pool
    instead of leaking a fresh ThreadPoolExecutor per query."""
    from spark_rapids_tpu.io.decode_pool import (
        decode_pool_size, get_decode_pool,
    )
    out = _write_multi_row_group_parquet(tmp_path)
    s = _v2_session()
    for _ in range(3):
        assert len(s.read.parquet(out).collect()) == 200
    pool = get_decode_pool(1)  # does not shrink the existing pool
    assert pool is get_decode_pool(1)
    size = decode_pool_size()
    n = sum(1 for t in threading.enumerate()
            if t.name.startswith("rapids-decode"))
    assert n <= size, (n, size)
