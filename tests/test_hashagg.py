"""MXU slot-aggregation tests (kernels/hashagg.py): correctness vs the
CPU oracle, engagement on eligible plans, and the exact-fallback paths
(wide key range, NaN floats, unsupported aggs)."""

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.dataframe import Column
from spark_rapids_tpu.exprs.aggregates import (
    Average, Count, First, Last, Max, Min, Sum,
)
from spark_rapids_tpu.exprs.base import Alias, ColumnRef

from compare import assert_tpu_cpu_equal, cpu_session, tpu_session


def _mxu_engaged(session) -> bool:
    return any(isinstance(ms, dict) and ms.get("mxuAggBatches", 0) > 0
               for ms in session.last_metrics.values())


def _data(n=4000, key_range=97, with_nan=False):
    rng = np.random.RandomState(5)
    keys = [None if i % 13 == 0 else int(k)
            for i, k in enumerate(rng.randint(0, key_range, n))]
    vals = [None if i % 7 == 0 else int(v)
            for i, v in enumerate(rng.randint(-10**9, 10**9, n))]
    fl = [None if i % 5 == 0 else float(f)
          for i, f in enumerate((rng.rand(n) * 1e6 - 5e5).round(3))]
    if with_nan:
        fl[17] = float("nan")
    return {"k": (T.INT, keys), "v": (T.LONG, vals), "f": (T.DOUBLE, fl)}


def _q(s, data):
    df = s.create_dataframe(data, num_partitions=3)
    return df.group_by("k").agg(
        Column(Alias(Sum(ColumnRef("v")), "sv")),
        Column(Alias(Count(ColumnRef("v")), "cv")),
        Column(Alias(Sum(ColumnRef("f")), "sf")),
        Column(Alias(Average(ColumnRef("f")), "af")),
        Column(Alias(Average(ColumnRef("v")), "av")),
    )


def test_mxu_agg_matches_cpu_oracle():
    assert_tpu_cpu_equal(
        lambda s: _q(s, _data()), approx=True,
        confs={"spark.rapids.sql.variableFloatAgg.enabled": True})


def test_mxu_agg_engages_and_is_exact_for_ints():
    conf = {"spark.rapids.sql.variableFloatAgg.enabled": True}
    tpu = tpu_session(**conf)
    cpu = cpu_session(**conf)
    data = _data()
    t_rows = {r[0]: r[1:3] for r in _q(tpu, data).collect()}
    c_rows = {r[0]: r[1:3] for r in _q(cpu, data).collect()}
    # int sum + count EXACT (limb recombination is bit-exact)
    assert t_rows == c_rows
    # the update agg really took the hash variant (sticky flag untouched)
    from spark_rapids_tpu.ops.tpu_exec import TpuHashAggregateExec
    aggs = []

    def walk(node):
        if isinstance(node, TpuHashAggregateExec) and node.mode == "update":
            aggs.append(node)
        for ch in getattr(node, "children", []):
            walk(ch)

    walk(tpu.last_physical_plan)
    assert aggs and all(a._hash_capable and not a._hash_disabled
                        for a in aggs)


def test_mxu_agg_falls_back_on_wide_key_range():
    """Key range far above the slot table: results still correct (sort
    path), and the fallback metric fires."""
    conf = {"spark.rapids.sql.variableFloatAgg.enabled": True}
    rng = np.random.RandomState(9)
    data = {
        "k": (T.LONG, [int(x) for x in
                       rng.randint(-10**17, 10**17, 2000)]),
        "v": (T.LONG, [int(x) for x in rng.randint(0, 100, 2000)]),
    }

    def q(s):
        df = s.create_dataframe(data, num_partitions=2)
        return df.group_by("k").agg(
            Column(Alias(Sum(ColumnRef("v")), "sv")))

    tpu = tpu_session(**conf)
    cpu = cpu_session(**conf)
    t = sorted(q(tpu).collect())
    c = sorted(q(cpu).collect())
    assert t == c
    fell_back = any(isinstance(ms, dict) and "hashAggFallback" in ms
                    for ms in tpu.last_metrics.values())
    assert fell_back, tpu.last_metrics


def test_mxu_agg_falls_back_on_nan_floats():
    conf = {"spark.rapids.sql.variableFloatAgg.enabled": True}
    data = _data(n=1000, with_nan=True)

    def q(s):
        df = s.create_dataframe(data, num_partitions=2)
        return df.group_by("k").agg(
            Column(Alias(Sum(ColumnRef("f")), "sf")))

    tpu = tpu_session(**conf)
    cpu = cpu_session(**conf)
    t = {r[0]: r[1] for r in q(tpu).collect()}
    c = {r[0]: r[1] for r in q(cpu).collect()}
    assert set(t) == set(c)
    for k, v in c.items():
        tv = t[k]
        if v is None or (isinstance(v, float) and v != v):
            assert tv is None or (isinstance(tv, float) and tv != tv), \
                (k, v, tv)
        else:
            assert abs(tv - v) <= 1e-6 * max(1.0, abs(v)), (k, v, tv)


def test_mxu_agg_minmax_first_last():
    """Round 5: min/max/first/last ride the slot index through the
    aggregates' own segment kernels — the plan keeps hash capability and
    the MXU path engages (metric-asserted)."""
    from spark_rapids_tpu.kernels.hashagg import hash_agg_capable
    assert hash_agg_capable(
        "update", [T.INT], [Max(ColumnRef("v")), Min(ColumnRef("v"))])

    def q(s):
        df = s.create_dataframe(_data(), num_partitions=2)
        return df.group_by("k").agg(
            Column(Alias(Max(ColumnRef("v")), "mx")),
            Column(Alias(Min(ColumnRef("v")), "mn")),
            Column(Alias(Min(ColumnRef("f")), "mf")),
            Column(Alias(Sum(ColumnRef("v")), "sv")))

    conf = {"spark.rapids.sql.variableFloatAgg.enabled": True}
    assert_tpu_cpu_equal(q, approx=True, confs=conf)
    tpu = tpu_session(**conf)
    q(tpu).collect()
    assert _mxu_engaged(tpu), tpu.last_metrics


def test_mxu_agg_first_last_ordered_input():
    # first/last are order-sensitive: use a single partition so the CPU
    # oracle sees the same row order as the device batch
    n = 600
    data = {"k": (T.INT, [i % 37 for i in range(n)]),
            "v": (T.LONG, [None if i % 11 == 0 else i for i in range(n)])}

    def q(s):
        df = s.create_dataframe(data, num_partitions=1)
        return df.group_by("k").agg(
            Column(Alias(First(ColumnRef("v")), "fv")),
            Column(Alias(Last(ColumnRef("v")), "lv")),
            Column(Alias(Count(ColumnRef("v")), "cv")))

    assert_tpu_cpu_equal(q)
    tpu = tpu_session()
    q(tpu).collect()
    assert _mxu_engaged(tpu), tpu.last_metrics


def test_mxu_agg_multi_key():
    """Round 5: multiple small-range keys pack into one slot index
    (mixed radix, NULL digit per nullable column)."""
    rng = np.random.RandomState(11)
    n = 3000
    data = {
        "a": (T.INT, [None if i % 17 == 0 else int(x)
                      for i, x in enumerate(rng.randint(0, 50, n))]),
        "b": (T.INT, [int(x) for x in rng.randint(-3, 4, n)]),
        "c": (T.BOOLEAN, [None if i % 23 == 0 else bool(x)
                          for i, x in enumerate(rng.randint(0, 2, n))]),
        "v": (T.LONG, [int(x) for x in rng.randint(-10**9, 10**9, n)]),
        "f": (T.DOUBLE, [float(x) for x in
                         (rng.rand(n) * 1e4 - 5e3).round(3)]),
    }

    def q(s):
        df = s.create_dataframe(data, num_partitions=3)
        return df.group_by("a", "b", "c").agg(
            Column(Alias(Sum(ColumnRef("v")), "sv")),
            Column(Alias(Count(ColumnRef("v")), "cv")),
            Column(Alias(Average(ColumnRef("f")), "af")),
            Column(Alias(Max(ColumnRef("v")), "mv")))

    conf = {"spark.rapids.sql.variableFloatAgg.enabled": True}
    assert_tpu_cpu_equal(q, approx=True, confs=conf)
    tpu = tpu_session(**conf)
    q(tpu).collect()
    # 50-ish * 8 * 3 slots << 8192: the packed path must engage
    assert _mxu_engaged(tpu), tpu.last_metrics


def test_mxu_agg_multi_key_product_fallback():
    """Two keys whose RANGE PRODUCT exceeds the table (each alone fits):
    exact sort fallback, correct results, fallback metric fires."""
    rng = np.random.RandomState(13)
    n = 2000
    data = {
        "a": (T.INT, [int(x) for x in rng.randint(0, 200, n)]),
        "b": (T.INT, [int(x) for x in rng.randint(0, 200, n)]),
        "v": (T.LONG, [int(x) for x in rng.randint(0, 100, n)]),
    }

    def q(s):
        df = s.create_dataframe(data, num_partitions=2)
        return df.group_by("a", "b").agg(
            Column(Alias(Sum(ColumnRef("v")), "sv")))

    tpu = tpu_session()
    cpu = cpu_session()
    assert sorted(q(tpu).collect()) == sorted(q(cpu).collect())
    fell_back = any(isinstance(ms, dict) and "hashAggFallback" in ms
                    for ms in tpu.last_metrics.values())
    assert fell_back, tpu.last_metrics


def test_mxu_agg_widened_table_conf():
    """tableSlots conf admits a key space the default table rejects."""
    rng = np.random.RandomState(13)
    n = 2000
    data = {
        "a": (T.INT, [int(x) for x in rng.randint(0, 200, n)]),
        "b": (T.INT, [int(x) for x in rng.randint(0, 200, n)]),
        "v": (T.LONG, [int(x) for x in rng.randint(0, 100, n)]),
    }

    def q(s):
        df = s.create_dataframe(data, num_partitions=2)
        return df.group_by("a", "b").agg(
            Column(Alias(Sum(ColumnRef("v")), "sv")))

    conf = {"spark.rapids.sql.agg.mxuHash.tableSlots": 65536}
    tpu = tpu_session(**conf)
    cpu = cpu_session()
    assert sorted(q(tpu).collect()) == sorted(q(cpu).collect())
    assert _mxu_engaged(tpu), tpu.last_metrics


def test_mxu_agg_keyless_and_empty():
    from spark_rapids_tpu import functions as F

    def q(s):
        df = s.create_dataframe(_data(n=500), num_partitions=2)
        return df.filter(F.col("v") > 10**10).agg(  # empty after filter
            Column(Alias(Count(ColumnRef("v")), "c")),
            Column(Alias(Sum(ColumnRef("v")), "s")))

    assert_tpu_cpu_equal(q)

    def q2(s):
        df = s.create_dataframe(_data(n=500), num_partitions=2)
        return df.agg(Column(Alias(Count(ColumnRef("v")), "c")),
                      Column(Alias(Sum(ColumnRef("v")), "s")))

    assert_tpu_cpu_equal(q2)


def test_mxu_agg_negative_and_date_keys():
    rng = np.random.RandomState(4)
    # dates are epoch-day ints in this engine's host model
    dates = [None if i % 9 == 0 else 19723 + int(d)
             for i, d in enumerate(rng.randint(0, 300, 1500))]
    data = {
        "d": (T.DATE, dates),
        "k": (T.INT, [int(x) for x in rng.randint(-500, 500, 1500)]),
        "v": (T.LONG, [int(x) for x in rng.randint(-100, 100, 1500)]),
    }
    assert_tpu_cpu_equal(
        lambda s: s.create_dataframe(data, num_partitions=2)
        .group_by("d").agg(Column(Alias(Sum(ColumnRef("v")), "sv"))))
    assert_tpu_cpu_equal(
        lambda s: s.create_dataframe(data, num_partitions=2)
        .group_by("k").agg(Column(Alias(Sum(ColumnRef("v")), "sv")),
                           Column(Alias(Count(ColumnRef("v")), "cv"))))
