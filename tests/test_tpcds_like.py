"""TPC-DS-like query correctness at SF0.1: every query runs on the TPU
engine and the CPU engine and must agree (TpcdsLikeSpark suite analogue)."""

import pytest

from spark_rapids_tpu.benchmarks.tpcds_like import QUERIES, register_tpcds

from compare import assert_tpu_cpu_equal

SF = 0.1


# The reference runs its whole tpcds suite with variableFloatAgg on,
# except q67/q70 (tpcds_test.py:21-50) — mirror that so float sums/avgs
# genuinely run on the device plan instead of falling back.
NO_VAR_AGG = {"q67", "q70"}


@pytest.mark.parametrize("qname", sorted(QUERIES.keys()))
def test_tpcds_like_query(qname):
    def build(s):
        register_tpcds(s, sf=SF, num_partitions=3)
        return s.sql(QUERIES[qname])

    confs = {} if qname in NO_VAR_AGG else \
        {"spark.rapids.sql.variableFloatAgg.enabled": True}
    assert_tpu_cpu_equal(build, approx=True, ignore_order=False,
                         confs=confs)


def test_tpcds_reference_coverage_has_no_holes():
    """The suite covers the reference's FULL 103-query tpcds list
    (tpcds_test.py: q1..q99 with the q14/q23/q24/q39 a/b variants) with
    no holes and no skip markers — q72 and q77 in particular run as
    first-class parametrized cases, not gaps."""
    ab = {14, 23, 24, 39}
    reference = []
    for i in range(1, 100):
        if i in ab:
            reference += [f"q{i}a", f"q{i}b"]
        else:
            reference.append(f"q{i}")
    assert len(reference) == 103
    missing = [q for q in reference if q not in QUERIES]
    assert not missing, f"tpcds coverage holes: {missing}"
    assert "q72" in QUERIES and "q77" in QUERIES
    # every query is a live parametrized case: the conf split (NO_VAR_AGG)
    # only changes confs, it never skips
    assert NO_VAR_AGG < set(QUERIES)


def test_tpcds_bench_report(tmp_path):
    from compare import tpu_session
    from spark_rapids_tpu.benchmarks.bench_utils import run_bench
    s = tpu_session()
    register_tpcds(s, sf=0.05, num_partitions=2)
    path = str(tmp_path / "tpcds_report.json")
    rep = run_bench(s, "q55", lambda: s.sql(QUERIES["q55"]),
                    iterations=1, warmups=0, report_path=path)
    assert rep["result_rows"] >= 1
