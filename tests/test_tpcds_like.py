"""TPC-DS-like query correctness at SF0.1: every query runs on the TPU
engine and the CPU engine and must agree (TpcdsLikeSpark suite analogue)."""

import pytest

from spark_rapids_tpu.benchmarks.tpcds_like import QUERIES, register_tpcds

from compare import assert_tpu_cpu_equal

SF = 0.1


# The reference runs its whole tpcds suite with variableFloatAgg on,
# except q67/q70 (tpcds_test.py:21-50) — mirror that so float sums/avgs
# genuinely run on the device plan instead of falling back.
NO_VAR_AGG = {"q67", "q70"}


@pytest.mark.parametrize("qname", sorted(QUERIES.keys()))
def test_tpcds_like_query(qname):
    def build(s):
        register_tpcds(s, sf=SF, num_partitions=3)
        return s.sql(QUERIES[qname])

    confs = {} if qname in NO_VAR_AGG else \
        {"spark.rapids.sql.variableFloatAgg.enabled": True}
    assert_tpu_cpu_equal(build, approx=True, ignore_order=False,
                         confs=confs)


def test_tpcds_bench_report(tmp_path):
    from compare import tpu_session
    from spark_rapids_tpu.benchmarks.bench_utils import run_bench
    s = tpu_session()
    register_tpcds(s, sf=0.05, num_partitions=2)
    path = str(tmp_path / "tpcds_report.json")
    rep = run_bench(s, "q55", lambda: s.sql(QUERIES["q55"]),
                    iterations=1, warmups=0, report_path=path)
    assert rep["result_rows"] >= 1
