"""Spill engine v2 tests: async writer semantics, get-vs-spill races,
writer-thread fault surfacing, incremental-accounting invariants, chunked
disk frames, overlapped unspill (the async twin of test_mem.py's tier
mechanics; test_faults.py::test_spill_site_injection pins the synchronous
contract)."""

import io
import time

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch, device_to_host, host_to_device
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.fault import inject
from spark_rapids_tpu.fault.inject import InjectedFault
from spark_rapids_tpu.mem.catalog import BufferCatalog, SpillableBatch

from conftest import assert_batches_equal

DATA = {
    "x": (T.INT, [1, 2, 3, None, 5]),
    "d": (T.DOUBLE, [0.5, None, -1.25, 3.0, 2.75]),
    "s": (T.STRING, ["aa", None, "cc", "dd", ""]),
}

# array columns spill device<->host (the disk serializer predates arrays)
ARR_DATA = {
    "x": (T.INT, [1, 2, 3, None, 5]),
    "a": (T.ArrayType(T.LONG), [[1, 2], None, [], [3], [4, 5, 6]]),
}


def make_catalog(device_budget, host_budget=1 << 20, **extra):
    conf = RapidsConf({
        "spark.rapids.memory.tpu.spillBudgetBytes": device_budget,
        "spark.rapids.memory.host.spillStorageSize": host_budget,
        **extra,
    })
    return BufferCatalog(conf)


def batch():
    return host_to_device(HostBatch.from_pydict(DATA))


@pytest.fixture(autouse=True)
def _no_faults():
    yield
    inject.uninstall()


@pytest.mark.parametrize("async_enabled", [True, False])
def test_full_tier_cycle_bit_parity(async_enabled, tmp_path):
    """device -> host -> chunked disk -> device round trip is bit-identical
    for int/double/string/array columns, async and sync alike (tiny
    chunkBytes forces many frames per spill file)."""
    cat = make_catalog(
        device_budget=1, host_budget=1,
        **{"spark.rapids.sql.tpu.spill.async.enabled": async_enabled,
           "spark.rapids.sql.tpu.spill.chunkBytes": 64,
           "spark.rapids.shuffle.compression.codec": "zlib"})
    h1 = cat.register(batch(), priority=1)
    cat.register(batch(), priority=2)
    cat.drain_spills()
    assert h1.tier == SpillableBatch.TIER_DISK
    assert cat.metrics["spilled_to_disk"] >= 1
    assert cat.metrics["spill_to_disk_bytes"] > 0
    got = device_to_host(h1.get()).to_pydict()
    assert_batches_equal(HostBatch.from_pydict(DATA).to_pydict(), got)


@pytest.mark.parametrize("async_enabled", [True, False])
def test_host_tier_bit_parity_arrays(async_enabled):
    """device -> host -> device round trip is bit-identical for array
    columns too (the disk serializer predates arrays, so the host tier is
    their spill ceiling)."""
    cat = make_catalog(
        device_budget=50,
        **{"spark.rapids.sql.tpu.spill.async.enabled": async_enabled})
    h1 = cat.register(host_to_device(HostBatch.from_pydict(ARR_DATA)),
                      priority=1)
    cat.register(host_to_device(HostBatch.from_pydict(ARR_DATA)),
                 priority=2)
    cat.drain_spills()
    assert h1.tier == SpillableBatch.TIER_HOST
    got = device_to_host(h1.get()).to_pydict()
    assert_batches_equal(HostBatch.from_pydict(ARR_DATA).to_pydict(), got)


def test_sync_mode_is_eager():
    """async.enabled=false restores v1 semantics: the tier move completes
    before the triggering register returns — no drain needed."""
    cat = make_catalog(
        device_budget=50,
        **{"spark.rapids.sql.tpu.spill.async.enabled": False})
    h1 = cat.register(batch(), priority=1)
    cat.register(batch(), priority=2)
    assert h1.tier == SpillableBatch.TIER_HOST
    assert cat.metrics["spilled_to_host"] >= 1
    assert cat.metrics["spill_to_host_bytes"] > 0


def test_get_cancels_queued_spill():
    """A get() racing a spill the writer has not started wins: the handle
    stays device-resident and the spill is cancelled, not performed (one
    writer thread pinned by a slow fault keeps the second spill queued
    deterministically)."""
    inject.install("spill:slow=400ms@1")
    cat = make_catalog(
        device_budget=1,
        **{"spark.rapids.sql.tpu.spill.writer.threads": 1})
    h1 = cat.register(batch(), priority=1)
    h2 = cat.register(batch(), priority=2)   # picks h1: writer, stalled
    cat.register(batch(), priority=3)        # picks h2: queued behind it
    got = h2.get()                           # races the queued spill
    assert h2.tier == SpillableBatch.TIER_DEVICE
    assert got is not None
    cat.drain_spills()
    assert cat.metrics["spill_cancelled"] >= 1
    assert h1.tier == SpillableBatch.TIER_HOST  # the stalled one finished


def test_writer_fault_surfaces_at_get():
    """A spill failing on the writer thread reverts the handle to the
    device tier and surfaces the classified error ONCE at the consumer's
    next get(); the retry then succeeds against the untouched device
    copy."""
    inject.install("spill:oom@1")
    cat = make_catalog(device_budget=1)
    h1 = cat.register(batch(), priority=1)
    cat.register(batch(), priority=2)  # triggers h1's (failing) spill
    cat.drain_spills()
    assert h1.tier == SpillableBatch.TIER_DEVICE
    with pytest.raises(InjectedFault):
        h1.get()
    # error consumed; the device copy never moved
    got = device_to_host(h1.get()).to_pydict()
    assert_batches_equal(HostBatch.from_pydict(DATA).to_pydict(), got)
    assert cat.metrics["spilled_to_host"] == 0


def test_unspill_site_injection():
    """The rehydration path is instrumented: an unspill:oom surfaces from
    get() and a bare retry succeeds (the copy is still host-resident)."""
    cat = make_catalog(
        device_budget=50,
        **{"spark.rapids.sql.tpu.spill.async.enabled": False})
    h1 = cat.register(batch(), priority=1)
    cat.register(batch(), priority=2)
    assert h1.tier == SpillableBatch.TIER_HOST
    inject.install("unspill:oom@1")
    with pytest.raises(InjectedFault):
        h1.get()
    assert h1.tier == SpillableBatch.TIER_HOST
    got = device_to_host(h1.get()).to_pydict()
    assert_batches_equal(HostBatch.from_pydict(DATA).to_pydict(), got)


def test_counter_scan_invariant():
    """The incremental per-tier byte counters match a full scan at every
    quiesced point of the handle lifecycle (the plan_verify debug
    invariant)."""
    cat = make_catalog(device_budget=50, host_budget=1 << 20)
    handles = [cat.register(batch(), priority=i) for i in range(4)]
    cat.drain_spills()
    assert cat.verify_accounting() == []
    handles[0].get()
    cat.drain_spills()
    assert cat.verify_accounting() == []
    handles[1].close()
    assert cat.verify_accounting() == []
    for h in handles:
        if not h.closed:
            h.close()
    assert cat.verify_accounting() == []
    assert cat.device_bytes_in_use() == 0
    assert cat.host_bytes_in_use() == 0


def test_chunked_frame_roundtrip():
    """Chunked disk frames reproduce the payload exactly across chunk
    sizes (including degenerate whole-blob and empty payloads) and
    codecs."""
    from spark_rapids_tpu.mem.codec import (
        get_codec, read_chunked, write_chunked,
    )
    payloads = [b"", b"x", b"hello world " * 1000]
    for codec_name in ("copy", "zlib"):
        codec = get_codec(codec_name)
        for payload in payloads:
            for chunk in (0, 7, 64, 1 << 20):
                buf = io.BytesIO()
                write_chunked(buf, payload, codec, chunk)
                buf.seek(0)
                assert read_chunked(buf, codec) == payload


def test_prefetch_overlaps_unspill():
    """catalog.prefetch yields device batches in order with read-ahead:
    spilled handles count as prefetch hits, and results are identical to
    a plain get() loop."""
    cat = make_catalog(device_budget=50)
    handles = [cat.register(batch(), priority=i) for i in range(3)]
    cat.drain_spills()
    spilled = sum(1 for h in handles
                  if h.tier != SpillableBatch.TIER_DEVICE)
    assert spilled >= 1
    want = HostBatch.from_pydict(DATA).to_pydict()
    n = 0
    for db in cat.prefetch(handles):
        assert_batches_equal(want, device_to_host(db).to_pydict())
        n += 1
    assert n == len(handles)
    assert cat.metrics["unspill_prefetch_hits"] >= 1


def test_query_prefetch_hits_and_admission_balanced():
    """An end-to-end shuffle query under a tiny budget drives its spilled
    pieces through the prefetch read-ahead (hits recorded), leaves the
    admission semaphore fully released (held_depth()==0), and keeps the
    catalog counters scan-consistent."""
    import numpy as np
    from spark_rapids_tpu.runtime.device import DeviceRuntime
    from spark_rapids_tpu.session import TpuSparkSession

    DeviceRuntime.reset()
    try:
        conf = RapidsConf({
            "spark.rapids.sql.enabled": True,
            "spark.sql.shuffle.partitions": 4,
            "spark.rapids.sql.tpu.exchange.collapseLocal": False,
            "spark.sql.autoBroadcastJoinThreshold": -1,
            "spark.rapids.memory.tpu.spillBudgetBytes": 64 * 1024,
        })
        s = TpuSparkSession(conf)
        n = 20_000
        rng = np.random.RandomState(7)
        left = s.create_dataframe(
            {"k": rng.randint(0, 500, n).tolist(),
             "v": rng.randint(0, 100, n).tolist()}, num_partitions=3)
        right = s.create_dataframe(
            {"k": list(range(500)), "w": list(range(500))},
            num_partitions=2)
        rows = left.join(right, on="k", how="inner").collect()
        assert len(rows) == n
        mem = s.last_metrics.get("memory", {})
        assert mem.get("unspilled", 0) > 0, mem
        assert mem.get("unspill_prefetch_hits", 0) > 0, mem
        assert s.last_metrics.get("unspillPrefetchHits", 0) > 0
        assert s.runtime.semaphore.held_depth() == 0
        assert s.runtime.catalog.verify_accounting() == []
    finally:
        DeviceRuntime.reset()


def test_async_matches_sync_query_results():
    """The same tiny-budget join is bit-identical with the async writer on
    and off, and the async run still records spill activity."""
    import numpy as np
    from spark_rapids_tpu.runtime.device import DeviceRuntime
    from spark_rapids_tpu.session import TpuSparkSession

    def run(async_enabled):
        DeviceRuntime.reset()
        try:
            conf = RapidsConf({
                "spark.rapids.sql.enabled": True,
                "spark.sql.shuffle.partitions": 4,
                "spark.rapids.sql.tpu.exchange.collapseLocal": False,
                "spark.sql.autoBroadcastJoinThreshold": -1,
                "spark.rapids.memory.tpu.spillBudgetBytes": 64 * 1024,
                "spark.rapids.sql.tpu.spill.async.enabled": async_enabled,
            })
            s = TpuSparkSession(conf)
            n = 20_000
            rng = np.random.RandomState(5)
            left = s.create_dataframe(
                {"k": rng.randint(0, 500, n).tolist(),
                 "v": rng.randint(0, 100, n).tolist()}, num_partitions=3)
            right = s.create_dataframe(
                {"k": list(range(500)), "w": list(range(500))},
                num_partitions=2)
            rows = sorted(map(str, left.join(right, on="k").collect()))
            return rows, dict(s.last_metrics.get("memory", {}))
        finally:
            DeviceRuntime.reset()

    rows_async, mem_async = run(True)
    rows_sync, mem_sync = run(False)
    assert rows_async == rows_sync
    assert mem_async.get("spilled_to_host", 0) > 0, mem_async
    assert mem_sync.get("spilled_to_host", 0) > 0, mem_sync
