"""Whole-stage mesh-SPMD execution tests (parallel.mesh_spmd) over the
8-device virtual CPU mesh: fused producer->all_to_all->consumer programs
must be bit-identical to the host-driven mesh path and the CPU oracle,
fall back per-exchange when the partitioning cannot lower in-program, and
leave the semaphore/catalog/plan invariants clean."""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T

from tests.compare import assert_tpu_cpu_equal, tpu_session
from tests.test_mesh_shuffle import MESH_CONFS

# spmd is the DEFAULT since mesh SPMD v2 — SPMD_CONFS keeps the explicit
# opt-in spelling, SPMD_OFF_CONFS pins the host-driven mesh path
SPMD_CONFS = {**MESH_CONFS,
              "spark.rapids.sql.tpu.mesh.spmd.enabled": True}
SPMD_OFF_CONFS = {**MESH_CONFS,
                  "spark.rapids.sql.tpu.mesh.spmd.enabled": False}


def _people_df(sess, n=400, parts=5):
    cats = ["red", "green", "blue", None, "a-very-long-color-name-x", ""]
    rng = np.random.RandomState(3)
    return sess.create_dataframe({
        "name": [cats[i] for i in rng.randint(0, len(cats), n)],
        "age": rng.randint(0, 90, n).tolist(),
        "score": (rng.rand(n) * 10).round(4).tolist(),
    }, num_partitions=parts)


def _groupby(s):
    return _people_df(s).group_by("name").agg(
        F.sum(F.col("age")), F.count(F.col("age")),
        F.avg(F.col("score")))


def _spmd_vs_hostdriven(build):
    """Collect ``build`` under spmd-on and spmd-off sessions; the fused
    program must be BIT-identical to the host-driven mesh path (same
    collective, same row placement — docs/mesh.md's parity contract)."""
    on = tpu_session(**SPMD_CONFS)
    off = tpu_session(**SPMD_OFF_CONFS)
    rows_on = sorted(build(on).collect(), key=repr)
    rows_off = sorted(build(off).collect(), key=repr)
    assert rows_on == rows_off, (rows_on[:5], rows_off[:5])
    return on


# -- parity: fused vs host-driven vs CPU oracle ------------------------------


def test_spmd_groupby_parity():
    assert_tpu_cpu_equal(_groupby, approx=True, confs=SPMD_CONFS)
    sess = _spmd_vs_hostdriven(_groupby)
    assert sess.last_metrics.get("meshProgramDispatches", 0) >= 1, \
        sess.last_metrics


def test_spmd_repartition_roundrobin_parity():
    def build(s):
        return _people_df(s, n=200).repartition(6).select("age")
    assert_tpu_cpu_equal(build, confs=SPMD_CONFS, ignore_order=True)
    _spmd_vs_hostdriven(build)


def test_spmd_distinct_parity():
    def build(s):
        return _people_df(s, n=300).select("name").distinct()
    assert_tpu_cpu_equal(build, confs=SPMD_CONFS)
    _spmd_vs_hostdriven(build)


# -- fused-boundary economics ------------------------------------------------


def test_spmd_fused_metrics():
    """With spmd on and 8 virtual devices a two-stage shuffle query runs
    as ONE compiled program: >=1 fused boundary, ZERO blocking shuffle
    syncs, and the session reports which backend the mesh ran on."""
    s = tpu_session(**SPMD_CONFS)
    _groupby(s).collect()
    m = s.last_metrics
    assert m["meshProgramDispatches"] >= 1, m
    assert m["meshBoundariesFused"] >= 1, m
    assert m["shuffleSyncs"] == 0, m
    assert m["meshBackend"] == "cpu", m


def test_spmd_off_reports_zero_fusion():
    s = tpu_session(**SPMD_OFF_CONFS)
    _groupby(s).collect()
    m = s.last_metrics
    assert m["meshProgramDispatches"] == 0, m
    assert m["meshBoundariesFused"] == 0, m


def test_spmd_default_on():
    """Mesh SPMD v2 flips the default: a bare mesh session fuses without
    anyone setting mesh.spmd.enabled."""
    s = tpu_session(**MESH_CONFS)
    _groupby(s).collect()
    m = s.last_metrics
    assert m["meshProgramDispatches"] >= 1, m
    assert m["meshFallbacks"] == 0, m


# -- range partitioning fuses ------------------------------------------------


def test_spmd_range_sort_fuses_with_parity():
    """Mesh SPMD v2: range bounds are sampled, pooled (all_gather) and
    picked INSIDE the program (RangePartitioning.device_bounds_in_program)
    — the sort's exchange fuses instead of host-driving an eager
    prepare() sample, while the query keeps total order and CPU parity."""
    def build(s):
        return _people_df(s, n=300).sort(
            F.col("age").asc(), F.col("name").asc())
    assert_tpu_cpu_equal(build, approx=True, ignore_order=False,
                         confs=SPMD_CONFS)
    s = tpu_session(**SPMD_CONFS)
    build(s).collect()
    assert s.last_metrics["meshProgramDispatches"] >= 1, s.last_metrics
    _spmd_vs_hostdriven(build)


# -- fallback ----------------------------------------------------------------


def test_spmd_single_partition_falls_back_with_parity():
    """SinglePartitioning matches no PartitionSpec rule (each shard would
    see a private 'partition 0'): a keyless global aggregate's exchange
    stays host-driven with parity intact."""
    def build(s):
        return _people_df(s, n=200).agg(F.sum(F.col("age")),
                                        F.count(F.col("score")))
    assert_tpu_cpu_equal(build, approx=True, confs=SPMD_CONFS)


def test_spmd_autofallback_disabled_raises():
    s = tpu_session(**SPMD_CONFS, **{
        "spark.rapids.sql.tpu.mesh.spmd.autoFallback": False})
    q = _people_df(s, n=100).agg(F.sum(F.col("age")))
    with pytest.raises(RuntimeError, match="mesh-SPMD compatible"):
        q.collect()


# -- the in-program collective, unit-level -----------------------------------


def _decode_varlen(elems, offs, valid, total, string):
    out = []
    for r in range(total):
        if not valid[r]:
            out.append(None)
            continue
        seg = elems[int(offs[r]):int(offs[r + 1])]
        out.append(bytes(seg.tobytes()).decode("utf-8") if string
                   else tuple(int(x) for x in seg))
    return out


def test_exchange_batch_collective_unit():
    """exchange_batch_collective inside a hand-built shard_map: every
    (int, string, array) row lands exactly once on the device its pid
    names, across empty shards, NULLs, empty strings and empty arrays."""
    from spark_rapids_tpu.batch import HostBatch, host_to_device, \
        round_up_capacity
    from spark_rapids_tpu.parallel import mesh_spmd as MS
    from spark_rapids_tpu.parallel.mesh_shuffle import (
        exchange_batch_collective, make_mesh,
    )
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    mesh = make_mesh(4)
    n = 4
    devices = list(mesh.devices.flat)
    cap = 16
    strs = ["", None, "x" * 40, "ünïcødé", "s"]
    arrs = [[1, 2, 3], [], None, [7], [9, 9]]
    per_dev_rows = [9, 5, 0, 7]
    hosts = []
    for d, rows in enumerate(per_dev_rows):
        hosts.append(HostBatch.from_pydict({
            "i": (T.INT, [(d * 31 + r * 7) % 97 for r in range(rows)]),
            "s": (T.STRING, [strs[(d + r) % len(strs)]
                             for r in range(rows)]),
            "a": (T.ArrayType(T.LONG), [arrs[(d + r) % len(arrs)]
                                        for r in range(rows)]),
        }))
    dbs = [host_to_device(hb, capacity=cap) for hb in hosts]
    schema = dbs[0].schema
    ecaps = tuple(
        round_up_capacity(
            max(int(db.columns[ci].data.shape[0]) for db in dbs),
            minimum=16)
        if MS._is_varlen(f) else 0
        for ci, f in enumerate(schema.fields))
    pack = MS._pack_fn(schema, cap, ecaps)
    shards_per_payload = None
    for d in range(n):
        payloads = pack(jax.device_put(dbs[d], devices[d]))
        if shards_per_payload is None:
            shards_per_payload = [[] for _ in payloads]
        for pi, p in enumerate(payloads):
            shards_per_payload[pi].append(p)
    in_specs, flat_globals = [], []
    for shards in shards_per_payload:
        tail = shards[0].shape[1:]
        spec = MS._full_rank_spec(len(tail) + 1, sharded=True)
        flat_globals.append(jax.make_array_from_single_device_arrays(
            (n,) + tail, NamedSharding(mesh, spec), shards))
        in_specs.append(spec)

    def body(flat):
        b = MS._batch_from_payloads(schema, list(flat), cap, squeeze=True)
        pid = (b.columns[0].data % n).astype(jnp.int32)
        out = exchange_batch_collective(b, pid, n)
        pl = []
        for c in out.columns:
            if c.offsets is not None:
                pl += [c.data[None], c.offsets.astype(jnp.int32)[None],
                       c.validity[None]]
            else:
                pl += [c.data[None], c.validity[None]]
        pl.append(jnp.asarray(out.num_rows, jnp.int32).reshape(1))
        return pl

    prog = shard_map(body, mesh=mesh, in_specs=(tuple(in_specs),),
                     out_specs=P("data"))
    outs = [np.asarray(g) for g in prog(tuple(flat_globals))]

    # host expectation: row (d, r) -> device i % n
    sent = {}
    for d, rows in enumerate(per_dev_rows):
        for r in range(rows):
            i = (d * 31 + r * 7) % 97
            sent.setdefault(i % n, []).append(
                (i, strs[(d + r) % len(strs)], arrs[(d + r) % len(arrs)]))
    totals = outs[-1]
    for dest in range(n):
        tot = int(totals[dest])
        ivals = [int(v) for v in outs[0][dest][:tot]]
        ivalid = outs[1][dest]
        svals = _decode_varlen(outs[2][dest], outs[3][dest],
                               outs[4][dest], tot, string=True)
        avals = _decode_varlen(outs[5][dest], outs[6][dest],
                               outs[7][dest], tot, string=False)
        assert all(bool(v) for v in ivalid[:tot])
        got = sorted(zip(ivals, [s if s is not None else "\0N" for s
                                 in svals],
                         [a if a is not None else ("\0N",) for a
                          in avals]))
        exp = sorted((i, s if s is not None else "\0N",
                      tuple(a) if a is not None else ("\0N",))
                     for i, s, a in sent.get(dest, []))
        assert got == exp, f"dest {dest}: {got[:4]} vs {exp[:4]}"


# -- fault injection / recovery ----------------------------------------------


def test_spmd_device_lost_replays_from_lineage():
    want = sorted(_groupby(tpu_session(**SPMD_CONFS)).collect(), key=repr)
    s = tpu_session(**SPMD_CONFS, **{
        "spark.rapids.sql.tpu.faults.spec": "mesh:device_lost@1"})
    got = sorted(_groupby(s).collect(), key=repr)
    assert got == want
    m = s.last_metrics
    assert m["faultsInjected"] >= 1, m
    assert m["deviceLostCount"] >= 1, m
    assert m["meshProgramDispatches"] >= 1, m


# -- resource hygiene --------------------------------------------------------


def test_spmd_leaves_semaphore_and_catalog_clean():
    s = tpu_session(**SPMD_CONFS)
    rows = _groupby(s).collect()
    assert rows
    assert s.runtime.semaphore.held_depth() == 0
    s.runtime.catalog.drain_spills()
    assert s.runtime.catalog.verify_accounting() == []


# -- plan_verify sharding invariants -----------------------------------------


def _mesh_spec_op(root):
    stack, seen = [root], set()
    while stack:
        op = stack.pop()
        if id(op) in seen:
            continue
        seen.add(id(op))
        if isinstance(getattr(op, "_mesh_partition_specs", None), dict):
            return op
        stack.extend(getattr(op, "children", ()) or ())
    return None


def test_plan_verify_mesh_fixtures():
    from spark_rapids_tpu.analysis.plan_verify import (
        PlanInvariantError, verify_plan,
    )
    s = tpu_session(**SPMD_CONFS)
    _groupby(s).collect()
    root = s.last_physical_plan
    op = _mesh_spec_op(root)
    assert op is not None, "no op recorded mesh partition specs"
    good = op._mesh_partition_specs
    verify_plan(root)  # accept fixture: the executed fused plan

    def reject(**overrides):
        op._mesh_partition_specs = {**good, **overrides}
        try:
            with pytest.raises(PlanInvariantError):
                verify_plan(root)
        finally:
            op._mesh_partition_specs = good

    bad_specs = list(good["in_specs"])
    bad_specs[0] = P(None, "data")  # neither replicated nor data-leading
    reject(in_specs=bad_specs)
    missing = list(good["in_specs"])
    missing[0] = None  # undeclared spec
    reject(in_specs=missing)
    reject(reshards=["no-such-op"])  # reshard outside the stage subtree
    reject(reshards=[])  # fused stage must record its boundary
    reject(dmask=(True,))  # donation under sharding
    verify_plan(root)  # restored


# -- backend honesty ---------------------------------------------------------


def test_make_mesh_backend_switch_warns(monkeypatch, caplog):
    """A default platform too small for the requested mesh silently
    switching to CPU virtual devices is how a bench mislabels CPU scaling
    as TPU scaling — make_mesh must warn through the explain logger."""
    import spark_rapids_tpu.parallel.mesh_shuffle as MS
    cpu = jax.devices("cpu")

    class FakeDev:
        platform = "tpu"

    def fake_devices(platform=None):
        if platform == "cpu":
            return cpu
        return [FakeDev()]

    monkeypatch.setattr(MS.jax, "devices", fake_devices)
    with caplog.at_level(logging.WARNING,
                         logger="spark_rapids_tpu.explain"):
        mesh = MS.make_mesh(4)
    assert mesh.shape[MS.DATA_AXIS] == 4
    msgs = [r.getMessage() for r in caplog.records
            if r.name == "spark_rapids_tpu.explain"]
    assert any("falling back" in m and "cpu" in m for m in msgs), msgs
