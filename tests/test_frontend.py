"""Serve front door tests (ISSUE PR 16 acceptance list): end-to-end
over a real socket with bit-identical rows, the shared plan cache
spanning client connections (second client compiles nothing), the
result cache answering warm repeats with zero compiles AND zero
dispatches, its three invalidation edges (input mtime, conf signature,
device generation), cost-weighted admission, sentinel-driven admission
control shedding predicted deadline misses before execution, clean
drain accounting, per-tenant telemetry gauges, and the adaptive
micro-batch window's clamping."""

import os
import threading
import time

import pytest

from compare import tpu_session
from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch
from spark_rapids_tpu.obs import timeseries as obs_ts
from spark_rapids_tpu.serve import (
    DeadlineExceeded, FrontDoorClient, FrontDoorServer, ResultCache,
    ServeScheduler, result_cache,
)
from spark_rapids_tpu.serve import protocol

SQL = "SELECT k, SUM(v) AS s FROM events GROUP BY k"


@pytest.fixture(autouse=True)
def _clean_state():
    """The result cache and telemetry ring are process singletons —
    never let one test's entries serve another's queries."""
    saved_ring = obs_ts._RING
    result_cache().clear()
    yield
    result_cache().clear()
    obs_ts._RING = saved_ring


def _session(**confs):
    s = tpu_session(**confs)
    df = s.create_dataframe(
        {"k": [i % 5 for i in range(400)],
         "v": [(3 * i) % 97 for i in range(400)]}, num_partitions=2)
    s.register_view("events", df)
    return s


def _rows(batch):
    cols = batch.to_pydict()
    return sorted(zip(*[cols[name] for name in batch.schema.names]))


def _expected(s, sql=SQL):
    return _rows(s.execute(s.sql(sql).plan))


# -- wire protocol units ------------------------------------------------------


def test_wire_batch_roundtrip_json_and_arrow():
    """Both encodings must survive nulls, strings and doubles
    bit-identically."""
    hb = HostBatch.from_pydict({
        "s": (T.STRING, ["a", None, "", "δ"]),
        "i": (T.LONG, [1, None, -3, 2**40]),
        "d": (T.DOUBLE, [0.5, float("inf"), None, -0.0]),
    })
    for enc in ("json", "arrow"):
        wire = protocol.batch_to_wire(hb, enc)
        back = protocol.wire_to_batch(wire)
        assert back.to_pydict() == hb.to_pydict(), enc
        assert wire["encoding"] == enc


def test_wire_batch_rejects_malformed():
    with pytest.raises(protocol.ProtocolError):
        protocol.wire_to_batch({"names": ["a"], "types": []})


# -- end-to-end over a real socket -------------------------------------------


def test_socket_parity_and_second_client_compiles_zero():
    """Rows over the wire are bit-identical to in-process execution,
    and a second client CONNECTION compiles nothing — the plan cache
    (and the front door's statement cache pinning its entries) spans
    connections."""
    s = _session()
    # keep the in-process plan object alive for the whole test: the
    # shared plan cache's entries are weakly anchored to their logical
    # plan, so a throwaway plan would strand a dying entry on the
    # fingerprint and force one rebuild mid-sequence
    plan = s.sql(SQL).plan
    want = _rows(s.execute(plan))
    with FrontDoorServer(s) as srv:
        with FrontDoorClient("127.0.0.1", srv.port) as c1:
            out, m1 = c1.submit_sql(SQL, tenant="a", cache=False)
            assert _rows(out) == want
        with FrontDoorClient("127.0.0.1", srv.port) as c2:
            out2, m2 = c2.submit_sql(SQL, tenant="b", cache=False)
            assert _rows(out2) == want
            assert m2["compileCount"] == 0, m2
            assert m2["resultCacheHits"] == 0


def test_result_cache_warm_repeat_zero_compiles_zero_dispatches():
    """A repeat query answers from the result cache across
    connections: zero compiles, zero dispatches, same rows."""
    s = _session()
    want = _expected(s)
    with FrontDoorServer(s) as srv:
        with FrontDoorClient("127.0.0.1", srv.port) as c:
            _out, m1 = c.submit_sql(SQL)  # miss: executes + inserts
            assert m1["resultCacheHits"] == 0
        with FrontDoorClient("127.0.0.1", srv.port) as c2:
            out, m2 = c2.submit_sql(SQL)
            assert _rows(out) == want
            assert m2["resultCacheHits"] == 1, m2
            assert m2["compileCount"] == 0
            assert m2["dispatchCount"] == 0
            st = c2.stats()["frontend"]
            assert st["result_cache_hits"] == 1
            d = c2.drain()
            assert d["drained"] and d["held_depth"] == 0


def test_template_over_wire_matches_in_process():
    """The micro-query template path works over the socket and matches
    the in-process scheduler's rows."""
    from spark_rapids_tpu.serve.bench import _request_batch, _template
    s = tpu_session()
    tmpl = _template()
    batch = _request_batch(3, 64)
    sched = ServeScheduler(s)
    want = sched.submit_micro(tmpl, batch).result(timeout=120).to_pydict()
    with FrontDoorServer(s, scheduler=sched) as srv:
        srv.register_template(tmpl)
        with FrontDoorClient("127.0.0.1", srv.port) as c:
            out, _m = c.submit_template(tmpl.key, batch, tenant="a")
            assert out.to_pydict() == want


# -- result-cache invalidation edges -----------------------------------------


def test_result_cache_mtime_invalidation(tmp_path):
    """Touching an input file changes its (mtime_ns, size) identity:
    the repeat MUST re-execute (dispatches > 0), with the same rows."""
    s = tpu_session()
    df = s.create_dataframe(
        {"k": [i % 5 for i in range(256)],
         "v": [(3 * i) % 97 for i in range(256)]}, num_partitions=2)
    pq = str(tmp_path / "pq")
    df.write_parquet(pq)
    s.register_view("events", s.read.parquet(pq))
    want = _expected(s)
    with FrontDoorServer(s) as srv:
        with FrontDoorClient("127.0.0.1", srv.port) as c:
            c.submit_sql(SQL)
            _out, m_hit = c.submit_sql(SQL)
            assert m_hit["resultCacheHits"] == 1

            part = next(f for f in sorted(os.listdir(pq))
                        if f.endswith(".parquet"))
            path = os.path.join(pq, part)
            st = os.stat(path)
            os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 10**9))

            out, m = c.submit_sql(SQL)
            assert m["resultCacheHits"] == 0, m
            assert m["dispatchCount"] > 0
            assert _rows(out) == want


def test_result_cache_conf_signature_invalidation():
    """A plan-relevant conf change must MISS: the key carries the conf
    signature, so the repeat re-executes under the new conf."""
    s = _session()
    want = _expected(s)
    with FrontDoorServer(s) as srv:
        with FrontDoorClient("127.0.0.1", srv.port) as c:
            c.submit_sql(SQL)
            _out, m_hit = c.submit_sql(SQL)
            assert m_hit["resultCacheHits"] == 1

            s.conf.set("spark.sql.shuffle.partitions", 3)
            out, m = c.submit_sql(SQL)
            assert m["resultCacheHits"] == 0, m
            assert m["dispatchCount"] > 0
            assert _rows(out) == want


def test_result_cache_generation_invalidation():
    """A device-lost recovery bumps the runtime generation: entries
    built under the old device are dropped on fetch and the repeat
    re-executes on the recovered runtime."""
    from spark_rapids_tpu.runtime.device import DeviceRuntime
    DeviceRuntime.reset()
    try:
        s = _session()
        want = _expected(s)
        with FrontDoorServer(s) as srv:
            with FrontDoorClient("127.0.0.1", srv.port) as c:
                c.submit_sql(SQL)
                _out, m_hit = c.submit_sql(SQL)
                assert m_hit["resultCacheHits"] == 1

                DeviceRuntime.recover(s.conf)
                out, m = c.submit_sql(SQL)
                assert m["resultCacheHits"] == 0, m
                assert m["dispatchCount"] > 0
                assert _rows(out) == want
    finally:
        DeviceRuntime.reset()
        result_cache().clear()


def test_result_cache_cost_weighted_admission():
    """A cheap-compute / big-bytes result must be REJECTED: caching it
    would evict genuinely expensive results for no latency win."""
    cache = ResultCache(min_ns_per_byte=50.0)
    big = HostBatch.from_pydict(
        {"x": (T.LONG, list(range(4096)))})  # ~32 KiB
    # 1000 ns of recorded compute for ~32 KiB: way under 50 ns/byte
    assert cache.insert(("fp", "sig", "in"), None, big,
                        wall_ns=1000, conf=None) is False
    assert cache.stats()["result_cache_admission_rejects"] == 1
    assert len(cache) == 0


# -- sentinel-driven admission control ---------------------------------------


def test_admission_sheds_predicted_deadline_miss_before_executing(tmp_path):
    """With >= minRuns history records, a query whose predicted wall
    (median + K*MAD) already misses its deadline is shed at the front
    door: DeadlineExceeded taxonomy, no execution, per-tenant rollup."""
    s = _session(**{
        "spark.rapids.sql.tpu.history.dir": str(tmp_path / "h"),
    })
    with FrontDoorServer(s) as srv:
        with FrontDoorClient("127.0.0.1", srv.port) as c:
            # cache=False: a result-cache hit would skip execution and
            # never append the history records the predictor needs
            for _ in range(3):
                c.submit_sql(SQL, tenant="a", cache=False)
            before = c.stats()
            completed_before = before["scheduler"]["completed"]

            with pytest.raises(DeadlineExceeded):
                c.submit_sql(SQL, tenant="a", cache=False,
                             deadline_sec=1e-6)

            st = c.stats()
            assert st["frontend"]["admission_shed"] == 1
            assert st["frontend"]["admission_shed_by_tenant"] == {"a": 1}
            # shed BEFORE executing: nothing new completed
            assert st["scheduler"]["completed"] == completed_before
            ten = st["scheduler"]["tenants"]["a"]
            assert ten["deadline_exceeded"] == 1
            assert ten["failed"] == 1

            # the same query WITHOUT a deadline still executes fine
            out, m = c.submit_sql(SQL, tenant="a", cache=False)
            assert m["admissionShed"] == 0
            assert _rows(out) == _expected(s)


def test_admission_inactive_without_history_baseline():
    """No history subsystem -> no prediction -> never shed (a tight
    deadline still applies at execution, but admission stays out)."""
    s = _session()
    with FrontDoorServer(s) as srv:
        with FrontDoorClient("127.0.0.1", srv.port) as c:
            out, m = c.submit_sql(SQL, tenant="a", cache=False,
                                  deadline_sec=60.0)
            assert m["admissionShed"] == 0
            assert c.stats()["frontend"]["admission_shed"] == 0


# -- telemetry ----------------------------------------------------------------


def test_per_tenant_gauges_in_prometheus_export():
    """Per-tenant queue/inflight/deadline-miss gauges register with the
    telemetry ring and render as Prometheus series."""
    s = _session()
    _expected(s)  # an execute configures the process telemetry ring
    with FrontDoorServer(s) as srv:
        with FrontDoorClient("127.0.0.1", srv.port) as c:
            c.submit_sql(SQL, tenant="a", cache=False)
            c.submit_sql(SQL, tenant="b", cache=False)
    ring = obs_ts.ring()
    assert ring is not None
    text = ring.prometheus_text()
    for name in ("rapids_serve_tenant_a_queue_depth",
                 "rapids_serve_tenant_a_inflight",
                 "rapids_serve_tenant_a_deadline_miss",
                 "rapids_serve_tenant_b_queue_depth",
                 "rapids_serve_frontend_connections",
                 "rapids_serve_frontend_requests"):
        assert name in text, (name, text)


# -- adaptive micro-batch window ---------------------------------------------


def test_adaptive_batch_window_clamped():
    """The adaptive linger is bounded to [0, maxDelayMs]: zero with no
    observed arrivals, clamped to maxDelayMs under a sparse trickle,
    near-zero under a flood, and the static linger while telemetry is
    off."""
    s = tpu_session(**{
        "spark.rapids.sql.tpu.serve.batch.adaptive.enabled": True,
        "spark.rapids.sql.tpu.serve.batch.maxDelayMs": 20,
    })
    sched = ServeScheduler(s, autostart=False)
    assert sched._batch_adaptive is True

    obs_ts._RING = None
    assert sched._adaptive_delay_s() == pytest.approx(0.020)

    obs_ts._RING = obs_ts.TelemetryRing(interval_ms=1000, max_intervals=2)
    assert sched._adaptive_delay_s() == 0.0  # quiet: don't linger

    obs_ts.record_value("serve.arrivals", 1.0)  # sparse: 2/rate > max
    assert sched._adaptive_delay_s() == pytest.approx(0.020)

    for _ in range(500):  # flood (near the per-interval sample cap):
        obs_ts.record_value("serve.arrivals", 1.0)  # 2/rate ~ 8ms
    d = sched._adaptive_delay_s()
    assert 0.0 < d < 0.020
    sched.close()


def test_adaptive_off_keeps_static_window():
    s = tpu_session(**{
        "spark.rapids.sql.tpu.serve.batch.maxDelayMs": 20,
    })
    sched = ServeScheduler(s, autostart=False)
    assert sched._batch_adaptive is False
    sched.close()


# -- concurrency + drain ------------------------------------------------------


def test_concurrent_socket_clients_parity_and_clean_drain():
    """Two weighted tenants hammering one front door from concurrent
    connections: every response bit-identical, then a clean drain with
    zero held semaphore depth."""
    s = _session(**{
        "spark.rapids.sql.tpu.serve.tenant.a.weight": "2",
        "spark.rapids.sql.tpu.serve.tenant.b.weight": "1",
    })
    want = _expected(s)
    errors = []
    with FrontDoorServer(s) as srv:
        def worker(tenant):
            try:
                with FrontDoorClient("127.0.0.1", srv.port) as c:
                    for _ in range(4):
                        out, _m = c.submit_sql(SQL, tenant=tenant,
                                               cache=False)
                        if _rows(out) != want:
                            errors.append(f"parity:{tenant}")
            except Exception as e:  # surfaced via the errors list
                errors.append(f"{tenant}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in ("a", "b")]
        for t in threads:
            t.start()
        for t in threads:
            while t.is_alive():
                t.join(0.25)
        assert errors == []
        with FrontDoorClient("127.0.0.1", srv.port) as c:
            d = c.drain()
            assert d["drained"] is True
            assert d["held_depth"] == 0
            sched_stats = c.stats()["scheduler"]
            tens = sched_stats["tenants"]
            assert tens["a"]["completed"] == 4
            assert tens["b"]["completed"] == 4
            assert sched_stats["failed"] == 0
