"""Mesh SPMD v2 fused-join tests: hash/broadcast joins compiled INTO the
fused shard_map program (static bucketed output sizing, zero host syncs),
bit-identical to the host-driven mesh path and the CPU oracle across
1/2/4/8 virtual devices; bucket-overflow fallback; dict-encoded keys and
the encoded-materialization boundary; plan_verify join-rule fixtures."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T

from tests.compare import tpu_session
from tests.test_mesh_spmd import MESH_CONFS, SPMD_CONFS, SPMD_OFF_CONFS

# threshold 0 disables broadcast: the shuffled (hash) strategy runs
HASH_JOIN = {"spark.sql.autoBroadcastJoinThreshold": 0}
GROWTH_KEY = "spark.rapids.sql.tpu.mesh.spmd.join.growthFactor"


def _left_df(sess, n=200, parts=4):
    cats = ["red", "green", "blue", None, "a-very-long-color-name-x", ""]
    rng = np.random.RandomState(7)
    return sess.create_dataframe({
        "name": [cats[i] for i in rng.randint(0, len(cats), n)],
        "age": rng.randint(0, 90, n).tolist(),
    }, num_partitions=parts)


def _right_df(sess):
    return sess.create_dataframe({
        "name": ["red", "green", "blue", None, "missing", ""],
        "bonus": [1, 2, 3, 4, 5, 6],
    }, num_partitions=2)


def _join_query(s, how, strategy):
    left = _left_df(s)
    right = _right_df(s)
    return left.join(right, on="name", how=how)


def _rows(df):
    return sorted(df.collect(), key=repr)


def _cpu_rows(how, strategy):
    s = tpu_session(**{"spark.rapids.sql.enabled": False})
    return _rows(_join_query(s, how, strategy))


def _mesh_n_devices(monkeypatch, k):
    """Pin the session's shuffle mesh to the first ``k`` virtual devices
    (session._shuffle_mesh resolves make_mesh from the module at call
    time, so patching the module attribute sizes every new session)."""
    import spark_rapids_tpu.parallel.mesh_shuffle as MS
    real = MS.make_mesh

    def sized(n_devices=None):
        return real(k)

    monkeypatch.setattr(MS, "make_mesh", sized)


# -- fused-join parity matrix ------------------------------------------------


# The quick lane keeps one full-sweep combo per strategy plus the
# cheapest anti cases; the remaining hows ride the slow lane (the fused
# kernel is how-agnostic past the stitch masks, so one how per strategy
# exercises every compiled path — the slow sweep still proves the matrix)
_MATRIX = [
    pytest.param("inner", "hash"),
    pytest.param("left", "hash", marks=pytest.mark.slow),
    pytest.param("left_semi", "hash", marks=pytest.mark.slow),
    pytest.param("left_anti", "hash", marks=pytest.mark.slow),
    pytest.param("inner", "broadcast"),
    pytest.param("left", "broadcast", marks=pytest.mark.slow),
    pytest.param("left_semi", "broadcast", marks=pytest.mark.slow),
    pytest.param("left_anti", "broadcast"),
]


@pytest.mark.parametrize("how,strategy", _MATRIX)
def test_spmd_join_parity_matrix(monkeypatch, how, strategy):
    """inner/left/semi/anti x shuffled-hash/broadcast x 1/2/4/8 devices:
    the fused per-shard join (static bucketed sizing, build side
    replicated for broadcast) is bit-identical to spmd-off and the CPU
    oracle, with zero overflow fallbacks at the default growth factor."""
    confs = dict(SPMD_CONFS)
    if strategy == "hash":
        confs.update(HASH_JOIN)
    want = _cpu_rows(how, strategy)
    off = tpu_session(**{**confs,
                         "spark.rapids.sql.tpu.mesh.spmd.enabled": False})
    assert _rows(_join_query(off, how, strategy)) == want

    for k in (1, 2, 4, 8):
        _mesh_n_devices(monkeypatch, k)
        s = tpu_session(**confs)
        got = _rows(_join_query(s, how, strategy))
        assert got == want, (how, strategy, k, got[:4], want[:4])
        m = s.last_metrics
        assert m["meshJoinsFused"] >= 1, (how, strategy, k, m)
        assert m["meshFallbacks"] == 0, (how, strategy, k, m)


@pytest.mark.parametrize("how", ["right", "full"])
def test_spmd_join_outer_hash_parity(how):
    """right/full outer ride the shuffled path too (co-partitioned
    shards make every join type exact per shard).  A USING full join's
    key projection is a string Coalesce — not TPU-supported — so its
    plan root falls back to CPU and never enters the mesh pipeline:
    parity holds, but only 'right' asserts fusion."""
    confs = {**SPMD_CONFS, **HASH_JOIN}
    want = _cpu_rows(how, "hash")
    s = tpu_session(**confs)
    assert _rows(_join_query(s, how, "hash")) == want
    if how == "right":
        assert s.last_metrics["meshJoinsFused"] >= 1, s.last_metrics


def test_spmd_join_feeding_aggregation_parity():
    """join -> group_by: the fused-join stage's root is the MXU hash
    aggregate, whose program appends a trailing flags pseudo-batch with
    its OWN schema — the mesh unshard must rebuild each output against
    the schema recorded at trace time (one flags batch per shard), not
    assume root.output_schema for every payload list."""
    def q(s):
        left = _left_df(s)
        right = _right_df(s)
        return left.join(right, on="name", how="inner").group_by(
            "name").agg(F.sum(F.col("bonus")).alias("sb"))

    cpu = tpu_session(**{"spark.rapids.sql.enabled": False})
    want = _rows(q(cpu))
    s = tpu_session(**{**SPMD_CONFS, **HASH_JOIN})
    assert _rows(q(s)) == want
    m = s.last_metrics
    assert m["meshJoinsFused"] >= 1, m
    assert m["meshFallbacks"] == 0, m


def test_spmd_join_fused_economics():
    """The pinned acceptance shape: a hash join ACROSS a shuffle compiles
    into ONE fused program — zero blocking shuffle syncs, >=1 fused
    boundary, >=1 fused join, no fallback."""
    s = tpu_session(**SPMD_CONFS, **HASH_JOIN)
    out = _left_df(s).join(_right_df(s), on="name", how="inner") \
        .group_by("name").agg(F.sum(F.col("age")),
                              F.count(F.col("bonus")))
    rows = out.collect()
    assert rows
    m = s.last_metrics
    assert m["shuffleSyncs"] == 0, m
    assert m["meshBoundariesFused"] >= 1, m
    assert m["meshJoinsFused"] >= 1, m
    assert m["meshFallbacks"] == 0, m
    assert m["meshProgramDispatches"] >= 1, m


def test_spmd_join_with_pallas_probe_kernel_parity():
    """Mesh v2 fused join with the Pallas probe kernel engaged (interpret
    mode on the CPU mesh): bit-identical rows, still one fused program
    with zero shuffle syncs, and zero kernel fallbacks — the kernel tier
    is shard_map-compatible (docs/kernels.md)."""
    pallas_on = {
        "spark.rapids.sql.tpu.pallas.interpret": True,
    }
    pallas_off = {
        "spark.rapids.sql.tpu.pallas.strings.enabled": False,
        "spark.rapids.sql.tpu.pallas.gatherScatter.enabled": False,
        "spark.rapids.sql.tpu.pallas.joinProbe.enabled": False,
        "spark.rapids.sql.tpu.pallas.stringHash.enabled": False,
    }
    off = tpu_session(**SPMD_CONFS, **HASH_JOIN, **pallas_off)
    want = _rows(_join_query(off, "inner", "hash"))

    s = tpu_session(**SPMD_CONFS, **HASH_JOIN, **pallas_on)
    got = _rows(_join_query(s, "inner", "hash"))
    assert got == want, (got[:4], want[:4])
    m = s.last_metrics
    assert m["meshJoinsFused"] >= 1, m
    assert m["shuffleSyncs"] == 0, m
    assert m["meshFallbacks"] == 0, m
    assert m["pallasFallbackCount"] == 0, m


def test_spmd_join_empty_shards_parity():
    """2 distinct keys over 8 shards: most shards receive zero rows and
    the per-shard static join must stay exact through them."""
    def build(s):
        left = s.create_dataframe(
            {"k": ["a", "b"] * 30, "v": list(range(60))},
            num_partitions=4)
        right = s.create_dataframe(
            {"k": ["a", "z"], "w": [10, 20]}, num_partitions=2)
        return left.join(right, on="k", how="left")
    want = _rows(build(tpu_session(
        **{"spark.rapids.sql.enabled": False})))
    s = tpu_session(**SPMD_CONFS, **HASH_JOIN)
    assert _rows(build(s)) == want
    assert s.last_metrics["meshJoinsFused"] >= 1, s.last_metrics


# -- bucket overflow -> host-driven fallback ---------------------------------


def _dup_key_join(s):
    # heavily duplicated keys: the true pair count per shard far exceeds
    # a tiny growth factor's static bucket
    left = s.create_dataframe(
        {"k": ["x", "y"] * 100, "v": list(range(200))}, num_partitions=4)
    right = s.create_dataframe(
        {"k": ["x", "y"] * 10, "w": list(range(20))}, num_partitions=2)
    return left.join(right, on="k", how="inner")


def test_spmd_join_overflow_falls_back_with_parity():
    want = _rows(_dup_key_join(tpu_session(
        **{"spark.rapids.sql.enabled": False})))
    s = tpu_session(**SPMD_CONFS, **HASH_JOIN, **{GROWTH_KEY: 0.02})
    assert _rows(_dup_key_join(s)) == want
    m = s.last_metrics
    assert m["meshFallbacks"] >= 1, m
    assert m["meshProgramDispatches"] >= 1, m
    # the overflow is observable, not silent
    names = [e.name for e in s.query_history()[-1].events]
    assert "join_overflow_fallback" in names, names


def test_spmd_join_overflow_autofallback_disabled_raises():
    s = tpu_session(**SPMD_CONFS, **HASH_JOIN,
                    **{GROWTH_KEY: 0.02,
                       "spark.rapids.sql.tpu.mesh.spmd.autoFallback":
                       False})
    with pytest.raises(RuntimeError, match="growthFactor"):
        _dup_key_join(s).collect()


@pytest.mark.slow
def test_spmd_join_overflow_leaves_resources_clean():
    s = tpu_session(**SPMD_CONFS, **HASH_JOIN, **{GROWTH_KEY: 0.02})
    assert _dup_key_join(s).collect()
    assert s.runtime.semaphore.held_depth() == 0
    s.runtime.catalog.drain_spills()
    assert s.runtime.catalog.verify_accounting() == []


# -- fault injection through a FUSED join program ----------------------------


@pytest.mark.slow
def test_spmd_join_device_lost_replays_bit_identical():
    confs = {**SPMD_CONFS, **HASH_JOIN}
    want = _rows(_join_query(tpu_session(**confs), "inner", "hash"))
    s = tpu_session(**confs, **{
        "spark.rapids.sql.tpu.faults.spec": "mesh:device_lost@1"})
    got = _rows(_join_query(s, "inner", "hash"))
    assert got == want
    m = s.last_metrics
    assert m["faultsInjected"] >= 1, m
    assert m["deviceLostCount"] >= 1, m
    assert m["retryCount"] > 0, m
    assert m["meshJoinsFused"] >= 1, m
    assert s.runtime.semaphore.held_depth() == 0


# -- dict-encoded keys and the mesh materialization boundary -----------------


def _write_dict_parquet(tmp_path, sess):
    out = str(tmp_path / "pq")
    sess.create_dataframe({
        "name": (["red", "green", None, "blue", "red", ""] * 40),
        "age": list(range(240)),
    }, num_partitions=2).write_parquet(out)
    return out


def _scan_join(s, out):
    left = s.read.parquet(out)
    right = _right_df(s)
    return left.join(right, on="name", how="inner")


def test_mesh_exchange_materializes_encoded_with_parity(tmp_path):
    """Dict-encoded scan columns materialize before the host-driven mesh
    exchange (the wire moves decoded rows): parity with dict encoding
    off, plus the exchange/mesh_materialize instant and the
    meshEncodedMaterializedBytes metric account the bytes given up."""
    # threshold 0 forces the shuffled strategy: the encoded scan side must
    # actually cross a mesh exchange for the boundary to exist
    base = {**SPMD_OFF_CONFS, **HASH_JOIN,
            "spark.rapids.sql.tpu.scan.v2.enabled": True}
    out = _write_dict_parquet(tmp_path, tpu_session())
    s_on = tpu_session(**base)
    got = _rows(_scan_join(s_on, out))
    s_off = tpu_session(**base, **{
        "spark.rapids.sql.tpu.scan.dictEncoding.enabled": False})
    assert got == _rows(_scan_join(s_off, out))
    m = s_on.last_metrics
    assert m["meshEncodedMaterializedBytes"] > 0, m
    evs = [e for e in s_on.query_history()[-1].events
           if e.name == "mesh_materialize"]
    assert evs, [e.name for e in s_on.query_history()[-1].events][:40]
    assert sum(e.payload.get("bytes", 0) for e in evs) == \
        m["meshEncodedMaterializedBytes"], (evs, m)


def test_spmd_join_encoded_keys_parity(tmp_path):
    """Dict-encoded join keys through the FUSED mesh join: parity with
    dictKeys off and with spmd off."""
    out = _write_dict_parquet(
        tmp_path, tpu_session())
    base = {**SPMD_CONFS, **HASH_JOIN,
            "spark.rapids.sql.tpu.scan.v2.enabled": True}
    s = tpu_session(**base)
    got = _rows(_scan_join(s, out))
    s_nokeys = tpu_session(**base, **{
        "spark.rapids.sql.tpu.join.dictKeys.enabled": False})
    assert got == _rows(_scan_join(s_nokeys, out))
    s_off = tpu_session(**{
        **base, "spark.rapids.sql.tpu.mesh.spmd.enabled": False})
    assert got == _rows(_scan_join(s_off, out))


def test_spmd_join_encoded_keys_overflow_fallback_parity(tmp_path):
    """Encoded keys INTERACTING with the overflow fallback: a bucket
    overflow reruns the stage host-driven with the encoded corridor still
    on, bit-identical to the relaxed-growth fused run."""
    out = _write_dict_parquet(tmp_path, tpu_session())
    base = {**SPMD_CONFS, **HASH_JOIN,
            "spark.rapids.sql.tpu.scan.v2.enabled": True}
    want = _rows(_scan_join(tpu_session(**base), out))
    s = tpu_session(**base, **{GROWTH_KEY: 0.01})
    assert _rows(_scan_join(s, out)) == want
    assert s.last_metrics["meshFallbacks"] >= 1, s.last_metrics


# -- plan_verify join rules --------------------------------------------------


def test_plan_verify_fused_join_fixtures():
    """Verifier accept/reject over a REAL fused-join stage: undeclared
    leaf specs in the join subtree, out-of-subtree join ids, replicated
    leaves that are not P(), and replicated join outputs all reject;
    an exchange-free (broadcast-join-only) stage shape is legal."""
    from spark_rapids_tpu.analysis.plan_verify import (
        PlanInvariantError, verify_plan,
    )
    from tests.test_mesh_spmd import _mesh_spec_op
    s = tpu_session(**SPMD_CONFS, **HASH_JOIN)
    _join_query(s, "inner", "hash").collect()
    root = s.last_physical_plan
    op = _mesh_spec_op(root)
    assert op is not None, "no op recorded mesh partition specs"
    good = op._mesh_partition_specs
    assert good["joins"], good
    verify_plan(root)

    def reject(**overrides):
        op._mesh_partition_specs = {**good, **overrides}
        try:
            with pytest.raises(PlanInvariantError):
                verify_plan(root)
        finally:
            op._mesh_partition_specs = good

    missing = list(good["in_specs"])
    missing[0] = None  # undeclared-spec leaf in a fused join subtree
    reject(in_specs=missing)
    reject(joins=["no-such-op"])  # join outside the stage subtree
    # a data-sharded leaf claimed as a broadcast build side must reject
    sharded = [i for i, sp in enumerate(good["in_specs"])
               if not all(a is None for a in tuple(sp))]
    reject(replicated=[sharded[0]])
    from jax.sharding import PartitionSpec as P
    if good["out_specs"]:
        bad_out = list(good["out_specs"])
        bad_out[0] = P()  # a fused join's output must be data-sharded
        reject(out_specs=bad_out)
    # reshard-free is legal when a join fused (broadcast-only stages),
    # but only alongside its joins — both empty must still reject
    op._mesh_partition_specs = {**good, "reshards": []}
    try:
        verify_plan(root)
    finally:
        op._mesh_partition_specs = good
    reject(reshards=[], joins=[])
    verify_plan(root)
