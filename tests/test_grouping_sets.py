"""ROLLUP / CUBE / GROUPING SETS over the Expand exec (GpuExpandExec's
grouping-sets plan shape)."""

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T

from compare import assert_tpu_cpu_equal, tpu_session

DATA = {"a": (T.STRING, ["x", "x", "y", "y", "y", None]),
        "b": (T.INT, [1, 2, 1, 1, None, 1]),
        "v": (T.DOUBLE, [10.0, 20.0, 5.0, 15.0, 2.0, 8.0])}


def test_rollup_dataframe():
    def build(s):
        df = s.create_dataframe(DATA, num_partitions=2)
        return (df.rollup("a", "b")
                .agg(F.sum("v").alias("sv"), F.count("v").alias("cv"),
                     F.grouping_id().alias("gid"))
                .order_by("gid", "a", "b"))

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)

    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    rows = (df.rollup("a", "b")
            .agg(F.sum("v").alias("sv"), F.grouping_id().alias("gid"))
            .order_by("gid", "a", "b").collect())
    # grand total row: both keys masked, gid = 0b11 = 3
    assert rows[-1] == (None, None, 60.0, 3)
    # (a)-level subtotals: gid = 1
    lvl1 = {r[0]: r[2] for r in rows if r[3] == 1}
    assert lvl1 == {"x": 30.0, "y": 22.0, None: 8.0}
    # detail rows: gid = 0; natural NULLs preserved distinct from masks
    detail = [r for r in rows if r[3] == 0]
    assert (None, 1, 8.0, 0) in detail and ("y", None, 2.0, 0) in detail


def test_cube_dataframe():
    def build(s):
        df = s.create_dataframe(DATA, num_partitions=2)
        return (df.cube("a", "b")
                .agg(F.sum("v").alias("sv"),
                     F.grouping_id().alias("gid"))
                .order_by("gid", "a", "b"))

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)

    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    rows = (df.cube("a", "b")
            .agg(F.sum("v").alias("sv"), F.grouping_id().alias("gid"))
            .collect())
    # cube has 4 grouping sets; (b)-level (gid=2) must exist
    lvl_b = {r[1]: r[2] for r in rows if r[3] == 2}
    assert lvl_b == {1: 38.0, 2: 20.0, None: 2.0}


def test_rollup_sql():
    def build(s):
        s.register_view("t", s.create_dataframe(DATA, num_partitions=2))
        return s.sql(
            "SELECT a, b, sum(v) AS sv, grouping_id() AS gid FROM t "
            "GROUP BY ROLLUP(a, b) ORDER BY gid, a, b")

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_cube_sql():
    def build(s):
        s.register_view("t", s.create_dataframe(DATA, num_partitions=2))
        return s.sql(
            "SELECT a, b, sum(v) AS sv FROM t GROUP BY CUBE(a, b) "
            "ORDER BY a, b, sv")

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_grouping_sets_sql():
    def build(s):
        s.register_view("t", s.create_dataframe(DATA, num_partitions=2))
        return s.sql(
            "SELECT a, b, count(*) AS c FROM t "
            "GROUP BY GROUPING SETS ((a, b), (a), ()) "
            "ORDER BY a, b, c")

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_grouping_sets_dataframe_explicit():
    def build(s):
        df = s.create_dataframe(DATA, num_partitions=3)
        return (df.grouping_sets(["a", "b"], [(0, 1), (1,), ()])
                .agg(F.max("v").alias("mv"),
                     F.grouping_id().alias("gid"))
                .order_by("gid", "a", "b"))

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_duplicate_grouping_sets_emit_duplicate_rows():
    """Spark semantics (SPARK-33229): GROUPING SETS ((a), (a)) yields two
    copies of each group with the CORRECT (not doubled) aggregates."""
    s = tpu_session()
    df = s.create_dataframe({"a": (T.STRING, ["x", "x", "y"]),
                             "v": (T.INT, [1, 2, 3])}, num_partitions=2)
    rows = sorted(df.grouping_sets(["a"], [(0,), (0,)])
                  .agg(F.sum("v").alias("sv")).collect())
    assert rows == [("x", 3), ("x", 3), ("y", 3), ("y", 3)]

    def build(s2):
        d = s2.create_dataframe({"a": (T.STRING, ["x", "x", "y"]),
                                 "v": (T.INT, [1, 2, 3])},
                                num_partitions=2)
        return (d.grouping_sets(["a"], [(0,), (0,)])
                .agg(F.sum("v").alias("sv")).order_by("a", "sv"))

    assert_tpu_cpu_equal(build, ignore_order=False)


def test_grouping_sets_rejects_bad_index():
    s = tpu_session()
    df = s.create_dataframe({"a": (T.STRING, ["x"]),
                             "v": (T.INT, [1])}, num_partitions=1)
    with pytest.raises(ValueError):
        df.grouping_sets(["a"], [(5,)])


def test_grouping_sets_bare_expression_and_soft_keywords():
    s = tpu_session()
    s.register_view("t", s.create_dataframe(DATA, num_partitions=2))
    # bare expression = one-element set (Spark shorthand)
    rows = s.sql("SELECT a, sum(v) AS sv FROM t "
                 "GROUP BY GROUPING SETS (a, ()) "
                 "ORDER BY a, sv").collect()
    assert (None, 60.0) in rows  # grand total present
    # rollup/cube/grouping/sets are NOT reserved words
    s.register_view("t2", s.create_dataframe(
        {"rollup": (T.INT, [1, 2]), "sets": (T.INT, [3, 4])},
        num_partitions=1))
    rows = s.sql("SELECT rollup, sets FROM t2 ORDER BY rollup").collect()
    assert rows == [(1, 3), (2, 4)]


def test_grouping_sets_pandas_path_rejected():
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=1)
    with pytest.raises(NotImplementedError):
        df.rollup("a").apply_in_pandas(lambda p: p, [("a", T.STRING)])
