"""Deterministic expression fuzzer: random typed expression trees
evaluated on the TPU engine and the CPU oracle must agree (the
random-data + random-shape layer of the reference's integration tests,
cf. integration_tests data_gen.py's randomized generators — here the
SHAPES are randomized too)."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T

from compare import assert_tpu_cpu_equal

ROWS = 160


def _base_data(seed):
    r = np.random.RandomState(seed)

    def with_nulls(vals, frac=0.15):
        out = list(vals)
        for i in range(len(out)):
            if r.rand() < frac:
                out[i] = None
        return out

    strings = ["", "a", "bb", "spark", "TPU engine", "x-y-z",
               "  pad  ", "zz top", "NULLish", "0123456789"]
    return {
        "rid": (T.LONG, list(range(ROWS))),  # unique, never null
        "i": (T.INT, with_nulls(r.randint(-1000, 1000, ROWS))),
        "j": (T.INT, with_nulls(r.randint(-5, 6, ROWS))),
        "l": (T.LONG, with_nulls(r.randint(-10**9, 10**9, ROWS))),
        "d": (T.DOUBLE, with_nulls((r.rand(ROWS) * 2000 - 1000)
                                   .round(4))),
        "e": (T.DOUBLE, with_nulls((r.rand(ROWS) * 4 - 2).round(6))),
        "b": (T.BOOLEAN, with_nulls(r.rand(ROWS) < 0.5)),
        "s": (T.STRING, with_nulls([strings[k] for k in
                                    r.randint(0, len(strings), ROWS)])),
        "dt": (T.DATE, with_nulls(r.randint(0, 20000, ROWS))),
    }


class _Gen:
    """Typed random expression-tree builder."""

    # "l" (1e9-scale) excluded: under the chip's f64 emulation,
    # symmetric trees can cancel 1e9-scale intermediates to ~0 where a
    # 3.5e-15 relative emulation difference exceeds the comparison's
    # absolute tolerance.  Bounded leaves keep full-cancellation error
    # below it.
    NUM_COLS = ["i", "j", "d", "e"]
    SMALL_COLS = ["j", "e"]

    def __init__(self, rng, df):
        self.r = rng
        self.df = df

    def pick(self, options):
        return options[self.r.randint(0, len(options))]

    def numeric(self, depth):
        if depth <= 0:
            if self.r.rand() < 0.25:
                return F.lit(float(self.r.randint(-50, 51)))
            return self.df[self.pick(self.NUM_COLS)]
        a = self.numeric(depth - 1)
        b = self.numeric(depth - 1)
        kind = self.r.randint(0, 11)
        if kind == 0:
            return a + b
        if kind == 1:
            return a - b
        if kind == 2:
            # products only over small leaves: bounds the value range so
            # later cancellation stays within comparison tolerance
            sa = self.df[self.pick(self.SMALL_COLS)]
            sb = self.df[self.pick(self.SMALL_COLS)]
            return sa * sb
        if kind == 3:
            if self.r.rand() < 0.3:
                # leaf/j exercises /0 -> NULL with a bounded quotient
                # (|i/j| <= 1000; j is small-integer and contains 0)
                return self.df[self.pick(self.NUM_COLS)] / self.df["j"]
            # bounded-denominator variant: |quotient| <= |a|, so later
            # subtractions cannot cancel emulation-scale residue
            sb = self.df[self.pick(self.SMALL_COLS)]
            return a / (F.abs(sb) + F.lit(1.0))
        if kind == 4:
            return F.abs(a)
        if kind == 5:
            return F.coalesce(a, b)
        if kind == 6:
            return F.when(self.boolean(depth - 1), a).otherwise(b)
        if kind == 7:
            return F.floor(a)
        if kind == 8:
            return F.length(self.string(depth - 1)).cast(T.DOUBLE)
        if kind == 9:
            # round-4 date parts over the date column
            part = self.pick([F.weekday, F.year, F.month])
            return part(self.df["dt"]).cast(T.DOUBLE)
        return -a

    def boolean(self, depth):
        if depth <= 0:
            return self.df["b"]
        kind = self.r.randint(0, 7)
        if kind == 0:
            return self.numeric(depth - 1) < self.numeric(depth - 1)
        if kind == 1:
            return self.numeric(depth - 1) >= self.numeric(depth - 1)
        if kind == 2:
            return self.boolean(depth - 1) & self.boolean(depth - 1)
        if kind == 3:
            return self.boolean(depth - 1) | self.boolean(depth - 1)
        if kind == 4:
            return ~self.boolean(depth - 1)
        if kind == 5:
            return self.string(depth - 1).is_null()
        return self.numeric(depth - 1) == self.numeric(depth - 1)

    def string(self, depth):
        if depth <= 0:
            return self.df["s"]
        kind = self.r.randint(0, 8)
        if kind == 0:
            return F.upper(self.string(depth - 1))
        if kind == 1:
            return F.lower(self.string(depth - 1))
        if kind == 2:
            return F.substring(self.string(depth - 1),
                               int(self.r.randint(1, 4)),
                               int(self.r.randint(1, 6)))
        if kind == 3:
            return F.concat(self.string(depth - 1),
                            self.string(depth - 1))
        if kind == 4:
            return F.trim(self.string(depth - 1))
        if kind == 5:
            return F.initcap(self.string(depth - 1))
        if kind == 6:
            return F.substring_index(self.string(depth - 1), "-",
                                     int(self.r.randint(1, 3)))
        return F.when(self.boolean(depth - 1),
                      self.string(depth - 1)).otherwise(
            self.string(depth - 1))


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_projection_trees(seed):
    """12 random projections per seed, depth <= 3, both engines agree."""
    def build(s):
        df = s.create_dataframe(_base_data(seed), num_partitions=3)
        g = _Gen(np.random.RandomState(1000 + seed), df)
        cols = []
        for k in range(6):
            cols.append(g.numeric(3).alias(f"n{k}"))
        for k in range(3):
            cols.append(g.boolean(2).alias(f"b{k}"))
        for k in range(3):
            cols.append(g.string(2).alias(f"s{k}"))
        return df.select(*cols)

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_filter_agg(seed):
    """Random filter + grouped aggregation pipelines agree."""
    def build(s):
        df = s.create_dataframe(_base_data(100 + seed),
                                num_partitions=3)
        g = _Gen(np.random.RandomState(2000 + seed), df)
        filtered = df.filter(g.boolean(2))
        return (filtered.group_by("j")
                .agg(F.sum(g.numeric(2)).alias("sx"),
                     F.count("*").alias("n"),
                     F.max(g.numeric(1)).alias("mx"))
                .order_by("j"))

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_sort_keys(seed):
    """Random multi-key sorts (mixed types/directions) agree."""
    def build(s):
        df = s.create_dataframe(_base_data(200 + seed),
                                num_partitions=3)
        r = np.random.RandomState(3000 + seed)
        keys = []
        for name in ["i", "s", "d", "b", "dt"]:
            if r.rand() < 0.6:
                c = df[name]
                keys.append(c.asc() if r.rand() < 0.5 else c.desc())
        keys.append(df["rid"].asc())  # unique non-null tiebreaker
        return df.order_by(*keys)

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)
