"""Data-plane throughput tests: buffer donation (safety + accounting),
the single-allocation k-way concat kernel's bit-parity with the pairwise
chain, the stop-aware read-ahead channel, async partition overlap, and
the bulk D2H metrics."""

import threading
import time

import jax
import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import (
    device_to_host, host_to_device, HostBatch, round_up_capacity,
)
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.kernels.layout import (
    concat_kway, concat_kway_run, concat_pair,
)
from spark_rapids_tpu.session import TpuSparkSession
from spark_rapids_tpu.utils import compile_registry as CR

from compare import tpu_session
from conftest import assert_batches_equal


def make_batch(data):
    return host_to_device(HostBatch.from_pydict(data))


# ---------------------------------------------------------------------------
# k-way concat: bit-parity with the pairwise chain
# ---------------------------------------------------------------------------


def _rand_data(rng, n, with_arrays=True):
    words = ["", "a", "hello world", "xyzzy", "long string value é"]
    data = {
        "i": (T.INT, [None if rng.rand() < 0.2 else int(rng.randint(-5, 99))
                      for _ in range(n)]),
        "d": (T.DOUBLE, [None if rng.rand() < 0.2 else float(rng.randn())
                         for _ in range(n)]),
        "s": (T.STRING, [None if rng.rand() < 0.2
                         else words[rng.randint(len(words))]
                         for _ in range(n)]),
    }
    if with_arrays:
        data["a"] = (T.ArrayType(T.LONG),
                     [None if rng.rand() < 0.2
                      else [int(x) for x in
                            rng.randint(0, 9, rng.randint(0, 4))]
                      for _ in range(n)])
    return data


def _pair_chain(batches, cap, byte_caps):
    acc = batches[0]
    for nxt in batches[1:]:
        acc = concat_pair(acc, nxt, cap, out_byte_caps=byte_caps or None)
    return acc


@pytest.mark.parametrize("k", [2, 3, 5])
def test_concat_kway_matches_pair_chain(rng, k):
    sizes = [int(rng.randint(1, 9)) for _ in range(k)]
    batches = [make_batch(_rand_data(rng, n)) for n in sizes]
    total = sum(sizes)
    cap = round_up_capacity(total)
    # byte caps bucketed from summed input byte capacities — the same
    # sizing concat_static uses for string AND array columns
    byte_caps = []
    for ci, f in enumerate(batches[0].schema.fields):
        if f.dtype.is_string or f.dtype.is_array:
            byte_caps.append(round_up_capacity(
                sum(int(b.columns[ci].data.shape[0]) for b in batches),
                minimum=16))
    got = concat_kway(batches, cap, out_byte_caps=byte_caps)
    exp = _pair_chain(batches, cap, byte_caps)
    assert got.capacity == exp.capacity == cap
    assert int(jax.device_get(got.num_rows)) == total
    for cg, ce in zip(got.columns, exp.columns):
        # bit-parity of every buffer, padding included
        assert cg.data.shape == ce.data.shape
        np.testing.assert_array_equal(np.asarray(jax.device_get(cg.data)),
                                      np.asarray(jax.device_get(ce.data)))
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(cg.validity)),
            np.asarray(jax.device_get(ce.validity)))
        if cg.offsets is not None:
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(cg.offsets)),
                np.asarray(jax.device_get(ce.offsets)))
    assert_batches_equal(device_to_host(exp).to_pydict(),
                         device_to_host(got).to_pydict())


def test_concat_kway_run_single_dispatch(rng):
    batches = [make_batch(_rand_data(rng, 4, with_arrays=False))
               for _ in range(3)]
    cap = round_up_capacity(12)
    before = CR.snapshot()
    out = concat_kway_run(batches, cap, out_byte_caps=[64])
    d = CR.delta(before, CR.snapshot())
    assert d["dispatches"] == 1  # the chain was an eager op storm
    assert int(jax.device_get(out.num_rows)) == 12


def test_concat_kway_after_take_head(rng):
    """take_head truncates num_rows WITHOUT repacking offsets, so a
    truncated input's offsets keep growing past its live rows — the k-way
    byte cursor must advance by offsets[num_rows] (live bytes), not
    offsets[-1], or every later input's bytes land past where the rebuilt
    offsets point (tpcds q49 regression: union of sorted+limited arms)."""
    from spark_rapids_tpu.kernels.layout import take_head
    full = [make_batch(_rand_data(rng, 8)) for _ in range(3)]
    heads = [take_head(b, 3) for b in full]
    total = 9
    cap = round_up_capacity(total)
    byte_caps = []
    for ci, f in enumerate(heads[0].schema.fields):
        if f.dtype.is_string or f.dtype.is_array:
            byte_caps.append(round_up_capacity(
                sum(int(b.columns[ci].data.shape[0]) for b in heads),
                minimum=16))
    got = concat_kway(heads, cap, out_byte_caps=byte_caps)
    exp = _pair_chain(heads, cap, byte_caps)
    assert_batches_equal(device_to_host(exp).to_pydict(),
                         device_to_host(got).to_pydict())


def test_concat_kway_default_byte_caps(rng):
    """Default byte capacity = summed input byte capacities, matching the
    chain's accumulated default."""
    a = make_batch({"s": (T.STRING, ["aa", "b"])})
    b = make_batch({"s": (T.STRING, ["cccc"])})
    cap = round_up_capacity(3)
    got = concat_kway([a, b], cap)
    exp = concat_pair(a, b, cap)
    assert got.columns[0].data.shape == exp.columns[0].data.shape
    assert_batches_equal(device_to_host(exp).to_pydict(),
                         device_to_host(got).to_pydict())


# ---------------------------------------------------------------------------
# donation: accounting + use-after-donate safety across pipeline paths
# ---------------------------------------------------------------------------


def _pipeline_queries(s):
    """One DataFrame per pipeline_inline path family: map (project/filter),
    aggregate update+merge (stage break), sort tail, limit, union,
    expand (grouping semantics via distinct)."""
    df = s.create_dataframe({
        "k": [i % 5 for i in range(400)],
        "v": [float(i) for i in range(400)],
        "s": [f"row{i % 7}" for i in range(400)],
    })
    agg = (df.filter(df["k"] > 0)
             .with_column("w", df["v"] * 2.0)
             .group_by("k")
             .agg(F.sum("w").alias("sw"), F.count("w").alias("c"),
                  F.min("v").alias("mn"))
             .order_by("k"))
    sorted_q = df.order_by(df["v"].desc()).limit(10)
    union_q = df.filter(df["k"] == 1).union(df.filter(df["k"] == 2))
    distinct_q = df.select("k").distinct().order_by("k")
    return [agg, sorted_q, union_q, distinct_q]


def test_donation_accounting_and_guard():
    """Every pipeline path runs under the armed use-after-donate guard —
    a donated buffer presented to any later dispatch or sync site raises —
    and the headline-shaped aggregate reports donatedBytes > 0."""
    s = tpu_session()
    with CR.donation_guard():
        results = [q.collect() for q in _pipeline_queries(s)]
        assert all(r is not None for r in results)
    m = s.last_metrics
    assert "donatedBytes" in m
    # re-run the aggregate alone for its own metrics delta
    with CR.donation_guard():
        agg = _pipeline_queries(s)[0]
        agg.collect()
    assert s.last_metrics["donatedBytes"] > 0


def test_donation_safe_with_cached_input_repeat():
    """A cached (spill-catalog) scan must never be donated: on backends
    that implement donation the second collect would hit deleted buffers.
    jax implements donation on CPU, so this test is load-bearing."""
    s = tpu_session()
    df = s.create_dataframe({"k": [i % 3 for i in range(100)],
                             "v": list(range(100))}).cache()
    q = df.group_by("k").agg(F.sum("v").alias("sv")).order_by("k")
    first = q.collect()
    second = q.collect()
    assert first == second


def test_donation_conf_off_parity():
    on = tpu_session()
    off = tpu_session(**{"spark.rapids.sql.tpu.donation.enabled": False})
    for q_on, q_off in zip(_pipeline_queries(on), _pipeline_queries(off)):
        assert q_on.collect() == q_off.collect()
    # with donation disabled nothing may be donated
    _pipeline_queries(off)[0].collect()
    assert off.last_metrics["donatedBytes"] == 0


def test_donating_programs_bypass_persistent_cache():
    """XLA:CPU mishandles donation aliasing in executables DESERIALIZED
    from the persistent compilation cache (use-after-free; jax 0.4.37).
    Donating programs must therefore never be written to it: their
    compiles run inside the no-persist scope with the cache hooks
    patched."""
    assert CR.donation_supported()
    from jax._src import compilation_cache as cc
    # hooks installed (wrapped functions carry the originals' names)
    assert cc.get_executable_and_time.__wrapped__ is not None
    assert cc.put_executable_and_time.__wrapped__ is not None
    with CR._no_persist_scope():
        assert cc.get_executable_and_time("k", None, None) == (None, None)
        assert cc.put_executable_and_time("k", "m", None, None, 0) is None


def test_donation_guard_catches_use_after_donate():
    """The guard itself must detect a genuine use-after-donate."""
    import jax.numpy as jnp
    donating = CR.instrumented_jit(lambda x: x + 1, label="guardtest",
                                   donate_argnums=(0,))
    plain = CR.instrumented_jit(lambda x: x * 2, label="guardtest2")
    with CR.donation_guard():
        x = jnp.arange(8, dtype=jnp.float32)
        donating(x)
        with pytest.raises(AssertionError, match="use-after-donate"):
            plain(x)


# ---------------------------------------------------------------------------
# async partition overlap + bulk D2H
# ---------------------------------------------------------------------------


def _multi_part_query(s):
    df = s.create_dataframe({
        "k": [i % 11 for i in range(600)],
        "v": [float(i) for i in range(600)],
    }, num_partitions=4)
    return (df.filter(df["v"] < 500.0)
              .group_by("k").agg(F.sum("v").alias("sv"),
                                 F.count("v").alias("c"))
              .order_by("k"))


def test_async_partitions_parity():
    on = tpu_session()
    off = tpu_session(
        **{"spark.rapids.sql.tpu.pipeline.asyncPartitions.enabled": False})
    assert _multi_part_query(on).collect() == \
        _multi_part_query(off).collect()


def test_async_bulk_collect_join_root():
    """A join as the plan root is not pipeline-viable: it exercises the
    bulk-collect path (all partitions dispatched, one sizes sync, one bulk
    D2H) — results must match the sequential per-batch path."""
    def q(s):
        left = s.create_dataframe({"k": [1, 2, 3, 4], "l": [10, 20, 30, 40]})
        right = s.create_dataframe({"k": [2, 3, 5], "r": [200, 300, 500]})
        return left.join(right, on="k").order_by("k").collect()

    on = tpu_session()
    off = tpu_session(
        **{"spark.rapids.sql.tpu.pipeline.asyncPartitions.enabled": False})
    assert q(on) == q(off)


def test_transfer_metrics_reported():
    s = tpu_session()
    q = _multi_part_query(s)
    q.collect()
    m = s.last_metrics
    for key in ("h2dBytes", "h2dTimeNs", "d2hBytes", "d2hTimeNs",
                "donatedBytes"):
        assert key in m, f"last_metrics missing {key}"
    assert m["h2dBytes"] > 0  # fresh (uncached) input staged this query
    assert m["d2hBytes"] > 0  # results came home


# ---------------------------------------------------------------------------
# stop-aware read-ahead channel
# ---------------------------------------------------------------------------


def test_readahead_channel_backpressure_and_stop():
    from spark_rapids_tpu.plan.physical import _ReadAheadChannel
    chan = _ReadAheadChannel(2)
    assert chan.put(1) and chan.put(2)
    blocked_result = []

    def producer():
        blocked_result.append(chan.put(3))  # blocks: channel full

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.05)
    assert t.is_alive()  # back-pressured, not dropped
    t0 = time.monotonic()
    chan.stop()
    t.join(timeout=2.0)
    assert not t.is_alive()
    # condition-variable wake, not a poll-interval tail
    assert time.monotonic() - t0 < 0.2
    assert blocked_result == [False]
    assert chan.get() is None  # stopped + drained


def test_readahead_channel_fifo_and_drain():
    from spark_rapids_tpu.plan.physical import _ReadAheadChannel
    chan = _ReadAheadChannel(4)
    for i in range(3):
        assert chan.put(i)
    assert [chan.get() for _ in range(3)] == [0, 1, 2]
    got = []

    def consumer():
        got.append(chan.get())  # blocks: channel empty

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.05)
    chan.put("x")
    t.join(timeout=2.0)
    assert got == ["x"]


def test_readahead_scan_pipeline_still_works(tmp_path):
    """End-to-end through the read-ahead staging thread (depth > 0) with
    the new channel: a file-backed scan query."""
    s = tpu_session(**{"spark.rapids.sql.tpu.stage.readAheadBatches": 2,
                       "spark.rapids.sql.reader.batchSizeRows": 16})
    cpu = TpuSparkSession(RapidsConf({"spark.rapids.sql.enabled": False}))
    df = cpu.create_dataframe({"k": [i % 4 for i in range(100)],
                               "v": list(range(100))})
    path = str(tmp_path / "pq")
    df.write_parquet(path, mode="overwrite")
    out = (s.read.parquet(path).group_by("k")
           .agg(F.sum("v").alias("sv")).order_by("k").collect())
    exp = {0: 1200, 1: 1225, 2: 1250, 3: 1275}
    got = {r[0]: r[1] for r in out}
    assert got == exp
