"""SQL frontend tests (qa_nightly_select_test analogue): each query runs on
the TPU and CPU engines and must agree."""

import pytest

from spark_rapids_tpu import types as T

from compare import assert_tpu_cpu_equal

STORE = {
    "item": (T.INT, [1, 2, 3, 1, 2, 3, 1, None, 5, 5]),
    "qty": (T.INT, [5, 30, 8, 2, 40, 1, 9, 3, None, 12]),
    "price": (T.DOUBLE, [1.5, 2.0, 0.5, 3.0, None, 2.5, 1.0, 4.5, 2.2, 9.9]),
    "name": (T.STRING, ["ham", "eggs", "spam", "ham", "eggs", "toast",
                        "spam", None, "jam", "jam"]),
}
ITEMS = {
    "item_sk": (T.INT, [1, 2, 3, 4]),
    "category": (T.STRING, ["meat", "dairy", "meat", "bread"]),
}


def run_sql(q):
    def build(s):
        df1 = s.create_dataframe(STORE, num_partitions=3)
        df1.create_or_replace_temp_view("store")
        df2 = s.create_dataframe(ITEMS)
        df2.create_or_replace_temp_view("items")
        return s.sql(q)
    return build


@pytest.mark.parametrize("q", [
    "SELECT item, qty FROM store",
    "SELECT * FROM store WHERE qty > 5",
    "SELECT item, qty * 2 AS dqty FROM store WHERE price IS NOT NULL",
    "SELECT item, sum(qty) AS s, count(*) AS n FROM store GROUP BY item",
    "SELECT name, avg(price) AS p FROM store GROUP BY name "
    "HAVING count(*) > 1",
    "SELECT item, qty FROM store ORDER BY qty DESC NULLS LAST, item LIMIT 5",
    "SELECT s.item, i.category, qty FROM store s JOIN items i "
    "ON s.item = i.item_sk WHERE qty < 50",
    "SELECT item FROM store WHERE name LIKE 'h%'",
    "SELECT item, CASE WHEN qty > 10 THEN 'big' ELSE 'small' END AS sz "
    "FROM store",
    "SELECT item FROM store WHERE item IN (1, 3, 5)",
    "SELECT DISTINCT name FROM store",
    "SELECT upper(name) AS u, length(name) AS l FROM store",
    "SELECT item, qty FROM store WHERE qty BETWEEN 5 AND 30",
    "SELECT cast(qty AS double) / 2 AS half FROM store WHERE qty IS NOT NULL",
    "SELECT item, sum(qty) AS s FROM store GROUP BY item "
    "ORDER BY s DESC NULLS LAST LIMIT 3",
    "SELECT name, count(*) AS n FROM store GROUP BY name "
    "UNION ALL SELECT category, count(*) AS n FROM items GROUP BY category",
    "SELECT a.item, a.s FROM (SELECT item, sum(qty) AS s FROM store "
    "GROUP BY item) a WHERE a.s > 10",
    "SELECT item, row_number() OVER (PARTITION BY item ORDER BY qty) AS rn "
    "FROM store WHERE item IS NOT NULL",
])
def test_sql_queries(q):
    ordered = "ORDER BY" in q and "GROUP BY item \nORDER" not in q
    assert_tpu_cpu_equal(run_sql(q), approx=True,
                         ignore_order=not ordered)


def test_sql_cross_and_semi():
    for q in [
        "SELECT s.item FROM store s LEFT SEMI JOIN items i "
        "ON s.item = i.item_sk",
        "SELECT s.item FROM store s LEFT ANTI JOIN items i "
        "ON s.item = i.item_sk",
        "SELECT s.item, i.item_sk FROM store s CROSS JOIN items i",
    ]:
        assert_tpu_cpu_equal(run_sql(q))


def test_sql_new_string_datetime_bitwise_functions():
    from compare import assert_tpu_cpu_equal
    data = {
        "s": ["a-b-c", "x-y", None, "plain"],
        "n": [3, 12, 7, 1],
        "t": [0, 1_600_000_000, 100, 200],
    }

    def q(sess):
        df = sess.create_dataframe(data)
        df.create_or_replace_temp_view("t1")
        return sess.sql(
            "SELECT split_part(s, '-', 2) AS p2, "
            "       regexp_replace(s, '[-]', '_') AS u, "
            "       concat_ws('/', s, s) AS d, "
            "       shiftleft(n, 1) AS n2, "
            "       from_unixtime(t) AS ts "
            "FROM t1")
    assert_tpu_cpu_equal(q)


def test_sql_count_distinct():
    from compare import assert_tpu_cpu_equal

    def q(sess):
        df = sess.create_dataframe({
            "k": [1, 1, 2, 2, 2, None, 1],
            "v": ["a", "a", "b", None, "c", "c", "d"],
        })
        df.create_or_replace_temp_view("cd")
        return sess.sql(
            "SELECT k, COUNT(DISTINCT v) AS cd, COUNT(v) AS c, "
            "       SUM(k) AS sk "
            "FROM cd GROUP BY k")
    assert_tpu_cpu_equal(q)


class TestFingerprintDedup:
    """Round-5 review regressions: dedup maps must key on structural
    fingerprints, not repr (repr omits frames/offsets/parameters)."""

    def test_asc_and_desc_rank_windows_are_distinct(self):
        from compare import assert_tpu_cpu_equal

        def q(s):
            df = s.create_dataframe(
                {"g": ["a", "a", "b", "b"], "x": [1, 2, 3, 4]},
                num_partitions=1)
            s.register_view("t_fp", df)
            return s.sql(
                "SELECT x, rank() OVER (ORDER BY x ASC) AS r_up, "
                "rank() OVER (ORDER BY x DESC) AS r_down FROM t_fp")

        assert_tpu_cpu_equal(q)

    def test_lag_offsets_are_distinct(self):
        from compare import assert_tpu_cpu_equal

        def q(s):
            df = s.create_dataframe(
                {"g": ["a", "a", "a", "a"], "x": [1, 2, 3, 4]},
                num_partitions=1)
            s.register_view("t_fp2", df)
            return s.sql(
                "SELECT x, lag(x, 1) OVER (PARTITION BY g ORDER BY x) "
                "AS l1, lag(x, 2) OVER (PARTITION BY g ORDER BY x) AS l2 "
                "FROM t_fp2")

        assert_tpu_cpu_equal(q)

    def test_percentile_spread_not_collapsed(self):
        from compare import cpu_session
        s = cpu_session()
        df = s.create_dataframe({"x": [1.0, 2.0, 3.0, 4.0, 5.0]},
                                num_partitions=1)
        s.register_view("t_fp3", df)
        rows = s.sql(
            "SELECT percentile(x, 0.9) - percentile(x, 0.1) AS spread "
            "FROM t_fp3").collect()
        assert abs(rows[0][0] - 3.2) < 1e-9, rows
