"""Fault-tolerance subsystem tests: taxonomy, unified retry policy,
deterministic injection per site x error class, deadline watchdog,
device-lost recovery with bit-identical replay, and per-partition CPU
fallback (the reference's "anything the GPU cannot finish must still
produce the Spark CPU answer" contract)."""

import time

import pytest

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.fault import inject
from spark_rapids_tpu.fault.errors import (
    DeviceLostError, ErrorClass, PartitionTimeout, classify_error,
    mark_non_retryable,
)
from spark_rapids_tpu.fault.inject import InjectedFault, parse_spec
from spark_rapids_tpu.fault.retry import RetryPolicy
from spark_rapids_tpu.fault.watchdog import partition_deadline
from spark_rapids_tpu.session import TpuSparkSession

from compare import tpu_session


@pytest.fixture(autouse=True)
def _clean_registry():
    """The injection registry is process-global: never leak an armed
    spec into the next test."""
    yield
    inject.uninstall()


def _xla_err(msg):
    return type("XlaRuntimeError", (Exception,), {})(msg)


DATA = {"k": [i % 5 for i in range(200)], "v": list(range(200))}


def _query(s):
    df = s.create_dataframe(DATA, num_partitions=2)
    return df.group_by("k").sum("v")


def _clean_rows():
    return sorted(_query(tpu_session()).collect())


# -- taxonomy ----------------------------------------------------------------


def test_classify_oom():
    assert classify_error(
        _xla_err("RESOURCE_EXHAUSTED: Out of memory allocating 1 bytes")
    ) is ErrorClass.RETRYABLE_OOM


@pytest.mark.parametrize("msg", [
    "INTERNAL: TPU worker crashed",
    "DATA_LOSS: checkpoint unreadable",
    "UNAVAILABLE: worker restarted mid-program",
    "INTERNAL: kernel fault detected",
])
def test_classify_device_lost(msg):
    assert classify_error(_xla_err(msg)) is ErrorClass.DEVICE_LOST


def test_classify_non_retryable():
    # user errors — even when the message mentions a status code
    assert classify_error(
        ValueError("RESOURCE_EXHAUSTED mentioned but wrong type")
    ) is ErrorClass.NON_RETRYABLE
    assert classify_error(KeyError("x")) is ErrorClass.NON_RETRYABLE
    # KeyboardInterrupt / SystemExit: never retried
    assert classify_error(KeyboardInterrupt()) is ErrorClass.NON_RETRYABLE
    assert classify_error(SystemExit(1)) is ErrorClass.NON_RETRYABLE
    # timeout classifies as device-lost (wedged == lost)
    assert classify_error(PartitionTimeout("t")) is ErrorClass.DEVICE_LOST
    # the donated-dispatch tag overrides message classification
    err = mark_non_retryable(_xla_err("RESOURCE_EXHAUSTED: donated"))
    assert classify_error(err) is ErrorClass.NON_RETRYABLE


def test_retry_policy_deterministic_backoff():
    p = RetryPolicy(4, 50)
    # pure function of the attempt index: 50, 100, 200ms — no jitter
    assert [p.delay_s(a) for a in (1, 2, 3)] == [0.05, 0.1, 0.2]
    assert RetryPolicy.from_conf(RapidsConf()).max_attempts == 3


# -- injection spec ----------------------------------------------------------


def test_parse_spec_grammar():
    rules = parse_spec("dispatch:oom@3;d2h:device_lost@1;"
                       "spill:slow=200ms@2;h2d:oom@4+")
    assert [(r.site, r.kind, r.at, r.persistent) for r in rules] == [
        ("dispatch", "oom", 3, False), ("d2h", "device_lost", 1, False),
        ("spill", "slow", 2, False), ("h2d", "oom", 4, True)]
    assert rules[2].duration_s == pytest.approx(0.2)
    assert parse_spec("") == [] and parse_spec(None) == []


@pytest.mark.parametrize("bad", [
    "nope:oom@1", "dispatch:frob@1", "dispatch:oom@0", "dispatch:oom",
    "dispatch:oom=5ms@1",
])
def test_parse_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_spec(bad)


def test_injection_matrix_site_by_class():
    """Every (site, error kind) pair fires exactly at its call index,
    with the declared classification."""
    for site in inject.SITES:
        for kind, cls in (("oom", ErrorClass.RETRYABLE_OOM),
                          ("device_lost", ErrorClass.DEVICE_LOST)):
            inject.install(f"{site}:{kind}@2")
            inject.maybe_fire(site)  # call 1: no fire
            with pytest.raises(InjectedFault) as ei:
                inject.maybe_fire(site)
            assert classify_error(ei.value) is cls
            inject.maybe_fire(site)  # call 3: one-shot, spent
        inject.install(f"{site}:slow=50ms@1")
        t0 = time.monotonic()
        inject.maybe_fire(site)
        assert time.monotonic() - t0 >= 0.04
    inject.uninstall()


def test_persistent_rule_fires_repeatedly():
    inject.install("dispatch:oom@2+")
    inject.maybe_fire("dispatch")
    for _ in range(3):
        with pytest.raises(InjectedFault):
            inject.maybe_fire("dispatch")


# -- end-to-end recovery -----------------------------------------------------


@pytest.mark.parametrize("spec", [
    "dispatch:oom@2", "dispatch:device_lost@1",
    "h2d:oom@1", "h2d:device_lost@1",
    "d2h:oom@1", "d2h:device_lost@1",
])
def test_injected_fault_recovers_with_identical_results(spec):
    """A fault at any data-plane site mid-query recovers (spill-retry or
    device replay) and the results are bit-identical to a clean run."""
    want = _clean_rows()
    s = tpu_session(**{"spark.rapids.sql.tpu.faults.spec": spec})
    got = sorted(_query(s).collect())
    assert got == want, (spec, got[:3], want[:3])
    m = s.last_metrics
    assert m["faultsInjected"] >= 1, m
    assert m["retryCount"] >= 1, m
    if "device_lost" in spec:
        assert m["deviceLostCount"] >= 1, m
    assert m["partitionFallbackCount"] == 0, m  # device replay sufficed


def test_exchange_site_recovers_split_path():
    """A device loss at the (non-collapsed) exchange split replays and
    the split cache's generation check recomputes from lineage."""
    confs = {"spark.rapids.sql.tpu.exchange.collapseLocal": False,
             "spark.sql.shuffle.partitions": 3}
    want = sorted(_query(tpu_session(**confs)).collect())
    s = tpu_session(**confs,
                    **{"spark.rapids.sql.tpu.faults.spec":
                       "exchange:device_lost@1"})
    got = sorted(_query(s).collect())
    assert got == want
    assert s.last_metrics["deviceLostCount"] >= 1


def test_spill_site_injection():
    """The catalog's spill-to-host path is instrumented: a slow fault
    stalls it, an injected OOM surfaces from the registering call.
    Synchronous mode pins the v1 contract (the async-writer surfacing of
    the same faults is covered in test_spill_async.py)."""
    from spark_rapids_tpu.batch import HostBatch, host_to_device
    from spark_rapids_tpu.mem.catalog import BufferCatalog

    def batch():
        return host_to_device(HostBatch.from_pydict(
            {"x": (__import__("spark_rapids_tpu.types", fromlist=["INT"])
                   .INT, list(range(64)))}))

    conf = RapidsConf({"spark.rapids.memory.tpu.spillBudgetBytes": 64,
                       "spark.rapids.sql.tpu.spill.async.enabled": False})
    inject.install("spill:oom@1")
    cat = BufferCatalog(conf)
    cat.register(batch(), priority=1)
    with pytest.raises(InjectedFault):
        cat.register(batch(), priority=2)  # budget forces the spill
    inject.install("spill:slow=50ms@1")
    cat2 = BufferCatalog(conf)
    cat2.register(batch(), priority=1)
    t0 = time.monotonic()
    cat2.register(batch(), priority=2)
    assert time.monotonic() - t0 >= 0.04
    assert cat2.metrics["spilled_to_host"] >= 1


def test_cpu_fallback_partition_parity():
    """Persistent device loss exhausts device replays; the partition
    completes through ops/cpu_exec with Spark-CPU-identical results —
    per-partition fallback, never whole-query abort."""
    want = _clean_rows()
    s = tpu_session(**{
        "spark.rapids.sql.tpu.faults.spec": "dispatch:device_lost@1+",
        "spark.rapids.sql.tpu.retry.backoffMs": 1,
    })
    got = sorted(_query(s).collect())
    assert got == want
    m = s.last_metrics
    assert m["partitionFallbackCount"] >= 1, m
    assert m["deviceLostCount"] >= 1, m
    assert m["backoffWallNs"] > 0, m


def test_fallback_disabled_surfaces_raw_error():
    s = tpu_session(**{
        "spark.rapids.sql.tpu.faults.spec": "dispatch:device_lost@1+",
        "spark.rapids.sql.tpu.retry.backoffMs": 1,
        "spark.rapids.sql.tpu.fallback.onDeviceError": False,
    })
    with pytest.raises(InjectedFault, match="injected device loss"):
        _query(s).collect()


def test_keyboard_interrupt_never_retried():
    """BaseException (KeyboardInterrupt/SystemExit) passes straight
    through the partition driver — no replay, no fallback."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.plan.physical import (
        ExecContext, PhysicalOp, _drive_partitions,
    )

    calls = {"n": 0}

    class Boom(PhysicalOp):
        def __init__(self):
            super().__init__([], T.Schema([]))

        def partitions(self, ctx):
            def gen():
                calls["n"] += 1
                raise KeyboardInterrupt()
                yield  # pragma: no cover

            return [gen()]

    ctx = ExecContext(RapidsConf(
        {"spark.rapids.sql.tpu.fallback.onDeviceError": True}))
    with pytest.raises(KeyboardInterrupt):
        _drive_partitions(Boom(), ctx, release_partial=False)
    assert calls["n"] == 1  # exactly one attempt


def test_user_error_not_retried():
    """NON_RETRYABLE user errors raise immediately: no replay burns
    attempts on a deterministic failure."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.plan.physical import (
        ExecContext, PhysicalOp, _drive_partitions,
    )

    calls = {"n": 0}

    class Bad(PhysicalOp):
        def __init__(self):
            super().__init__([], T.Schema([]))

        def partitions(self, ctx):
            def gen():
                calls["n"] += 1
                raise KeyError("user bug")
                yield  # pragma: no cover

            return [gen()]

    with pytest.raises(KeyError):
        _drive_partitions(Bad(), ExecContext(RapidsConf()),
                          release_partial=False)
    assert calls["n"] == 1


# -- deadline watchdog -------------------------------------------------------


def test_watchdog_context_manager_fires():
    with pytest.raises(PartitionTimeout):
        with partition_deadline(0.2, "unit"):
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                time.sleep(0.005)


def test_watchdog_disarmed_is_noop():
    with partition_deadline(0.0, "unit"):
        time.sleep(0.05)
    with partition_deadline(RapidsConf(), "unit"):  # default conf: off
        pass


def test_hung_partition_fails_fast_and_releases_permits():
    """Acceptance: under partition.timeoutSec=2 a hung partition fails
    with PartitionTimeout instead of stalling the suite, permits are
    released via the existing finally paths, and the next query on the
    same process works."""
    s = tpu_session(**{
        "spark.rapids.sql.tpu.partition.timeoutSec": 2.0,
        "spark.rapids.sql.tpu.retry.maxAttempts": 1,
        "spark.rapids.sql.tpu.fallback.onDeviceError": False,
        "spark.rapids.sql.tpu.faults.spec": "dispatch:slow=60000ms@1",
    })
    t0 = time.monotonic()
    with pytest.raises(PartitionTimeout):
        _query(s).collect()
    assert time.monotonic() - t0 < 15
    assert s.runtime.semaphore.held_depth() == 0
    # same process recovers: a clean session answers normally
    assert sorted(_query(tpu_session()).collect()) == _clean_rows()


def test_hung_partition_recovers_when_retries_allowed():
    """With replays allowed the timeout enters device-lost recovery and
    the query completes (the stall was one-shot)."""
    want = _clean_rows()
    s = tpu_session(**{
        "spark.rapids.sql.tpu.partition.timeoutSec": 1.0,
        "spark.rapids.sql.tpu.faults.spec": "dispatch:slow=60000ms@1",
    })
    got = sorted(_query(s).collect())
    assert got == want
    assert s.last_metrics["deviceLostCount"] >= 1


# -- device-lost recovery internals ------------------------------------------


def test_invalidate_device_tier_rescues_to_host():
    """Live device buffers are rescued to host on invalidation (the
    injected-loss case); host/disk tiers are untouched and handles
    re-upload lazily."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.batch import (
        HostBatch, device_to_host, host_to_device,
    )
    from spark_rapids_tpu.mem.catalog import BufferCatalog, SpillableBatch
    from conftest import assert_batches_equal

    data = {"x": (T.INT, [1, 2, None, 4])}
    cat = BufferCatalog(RapidsConf())
    h = cat.register(host_to_device(HostBatch.from_pydict(data)))
    assert h.tier == SpillableBatch.TIER_DEVICE
    assert cat.invalidate_device_tier() == 1
    assert h.tier == SpillableBatch.TIER_HOST
    got = device_to_host(h.get()).to_pydict()
    assert_batches_equal(HostBatch.from_pydict(data).to_pydict(), got)
    assert cat.metrics["device_invalidated"] == 1


def test_lost_handle_raises_classified_error():
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.batch import HostBatch, host_to_device
    from spark_rapids_tpu.mem.catalog import BufferCatalog, SpillableBatch

    cat = BufferCatalog(RapidsConf())
    h = cat.register(host_to_device(HostBatch.from_pydict(
        {"x": (T.INT, [1, 2, 3])})))
    # simulate an unrescuable loss (real device death: D2H fails too)
    h._device = None
    h.tier = SpillableBatch.TIER_LOST
    with pytest.raises(DeviceLostError) as ei:
        h.get()
    assert classify_error(ei.value) is ErrorClass.DEVICE_LOST


def test_runtime_recover_keeps_catalog_and_bumps_generation():
    from spark_rapids_tpu.runtime.device import DeviceRuntime

    DeviceRuntime.reset()
    try:
        conf = RapidsConf()
        rt = DeviceRuntime.get(conf)
        cat = rt.catalog
        g0 = DeviceRuntime.generation()
        rt2 = DeviceRuntime.recover(conf)
        assert DeviceRuntime.generation() == g0 + 1
        assert rt2.catalog is cat           # spill tiers survive
        assert rt2.semaphore is not rt.semaphore  # wedged permits don't
        assert DeviceRuntime.get(conf) is rt2
    finally:
        DeviceRuntime.reset()


def test_oom_retry_uses_unified_policy():
    """catalog.run_with_oom_retry is a thin wrapper over the unified
    policy: conf maxAttempts bounds it and injected OOMs (explicit
    classification) trigger the same spill machinery as real ones."""
    from spark_rapids_tpu.batch import HostBatch, host_to_device
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.mem.catalog import (
        BufferCatalog, SpillableBatch, run_with_oom_retry,
    )

    conf = RapidsConf({"spark.rapids.sql.tpu.retry.maxAttempts": 2,
                       "spark.rapids.sql.tpu.retry.backoffMs": 1})
    cat = BufferCatalog(conf)
    h = cat.register(host_to_device(HostBatch.from_pydict(
        {"x": (T.INT, [1, 2, 3])})))
    calls = {"n": 0}

    def thunk():
        calls["n"] += 1
        if calls["n"] == 1:
            raise InjectedFault("RESOURCE_EXHAUSTED (unit)",
                                ErrorClass.RETRYABLE_OOM)
        return "ok"

    assert run_with_oom_retry(cat, thunk) == "ok"
    assert calls["n"] == 2
    assert h.tier == SpillableBatch.TIER_HOST  # spilled by the handler

    # maxAttempts=2 -> a thunk failing twice exhausts the policy (a
    # fresh device-tier handle keeps the spill pass productive, so the
    # early freed==0 give-up doesn't shortcut the bound)
    cat.register(host_to_device(HostBatch.from_pydict(
        {"x": (T.INT, [4, 5, 6])})))
    calls2 = {"n": 0}

    def always():
        calls2["n"] += 1
        raise InjectedFault("RESOURCE_EXHAUSTED (unit)",
                            ErrorClass.RETRYABLE_OOM)

    with pytest.raises(InjectedFault):
        run_with_oom_retry(cat, always)
    assert calls2["n"] == 2


def test_session_metrics_clean_query_all_zero():
    s = tpu_session()
    _query(s).collect()
    m = s.last_metrics
    assert m["retryCount"] == 0 and m["deviceLostCount"] == 0
    assert m["partitionFallbackCount"] == 0 and m["faultsInjected"] == 0
    assert m["backoffWallNs"] == 0


def test_registry_uninstalled_after_query():
    """Persistent @N+ rules must not outlive the query: sites reached
    outside execute (no recovery machinery there) stay un-instrumented."""
    s = tpu_session(**{
        "spark.rapids.sql.tpu.faults.spec": "h2d:device_lost@1+",
        "spark.rapids.sql.tpu.retry.backoffMs": 1,
    })
    _query(s).collect()  # completes via recovery/fallback
    assert not inject.active()
    # a bare host_to_device outside any query must not raise
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.batch import HostBatch, host_to_device
    host_to_device(HostBatch.from_pydict({"x": (T.INT, [1, 2])}))


def test_recovery_repoints_ctx_at_live_runtime():
    """recover_device_lost must re-point the query context at the
    REBUILT runtime: replays dispatch to the live device and take
    admission on the live semaphore, not the dead ones."""
    from spark_rapids_tpu.fault.recovery import recover_device_lost
    from spark_rapids_tpu.plan.physical import ExecContext
    from spark_rapids_tpu.runtime.device import DeviceRuntime

    DeviceRuntime.reset()
    try:
        conf = RapidsConf()
        rt = DeviceRuntime.get(conf)
        ctx = ExecContext(conf, semaphore=rt.semaphore, device=rt.device)
        recover_device_lost(ctx)
        rt2 = DeviceRuntime.get(conf)
        assert rt2 is not rt
        assert ctx.semaphore is rt2.semaphore
        assert ctx.device is rt2.device
    finally:
        DeviceRuntime.reset()


def test_timeout_recovery_skips_rescue_copy():
    """A PartitionTimeout-triggered recovery must not attempt the rescue
    D2H (the device is wedged — a copy against it would block the
    recovery path): device-tier handles go straight to TIER_LOST."""
    from spark_rapids_tpu.batch import HostBatch, host_to_device
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.fault.recovery import recover_device_lost
    from spark_rapids_tpu.plan.physical import ExecContext
    from spark_rapids_tpu.mem.catalog import SpillableBatch
    from spark_rapids_tpu.runtime.device import DeviceRuntime

    DeviceRuntime.reset()
    try:
        conf = RapidsConf()
        rt = DeviceRuntime.get(conf)
        h = rt.catalog.register(host_to_device(HostBatch.from_pydict(
            {"x": (T.INT, [1, 2, 3])})))
        ctx = ExecContext(conf, semaphore=rt.semaphore, device=rt.device)
        recover_device_lost(ctx, PartitionTimeout("wedged"))
        assert h.tier == SpillableBatch.TIER_LOST
        with pytest.raises(DeviceLostError):
            h.get()
        # a crash-classified recovery on a responsive device DOES rescue
        rt2 = DeviceRuntime.get(conf)
        h2 = rt2.catalog.register(host_to_device(HostBatch.from_pydict(
            {"x": (T.INT, [4, 5])})))
        recover_device_lost(ctx, _xla_err("INTERNAL: worker crashed"))
        assert h2.tier == SpillableBatch.TIER_HOST
    finally:
        DeviceRuntime.reset()
