"""Streamed/chunked nested-loop join + spillable broadcast build side
(GpuBroadcastNestedLoopJoinExec.scala:305 streaming shape; broadcast
build batches registered with the buffer catalog)."""

from spark_rapids_tpu import types as T

from compare import _canon, cpu_session, tpu_session

SMALL_PAIRS = {"spark.rapids.sql.nestedLoopJoin.pairCapacity": 4096}


def _assert_equal_rows(cpu_rows, tpu_rows):
    a = _canon(cpu_rows, True, True)
    b = _canon(tpu_rows, True, True)
    assert len(a) == len(b), f"cpu={len(a)} tpu={len(b)}"
    for i, (ra, rb) in enumerate(zip(a, b)):
        assert ra == rb, f"row {i}: cpu={ra} tpu={rb}"


def _metric_ops(sess, name):
    return [op for op, ms in sess.last_metrics.items()
            if isinstance(ms, dict) and name in ms]


N_LEFT = 5000


def _left(s, parts=2):
    return s.create_dataframe(
        {"a": (T.INT, [i % 97 for i in range(N_LEFT)]),
         "v": (T.LONG, list(range(N_LEFT)))}, num_partitions=parts)


def _right(s):
    return s.create_dataframe(
        {"k": (T.INT, [10, 50, 96, 200]),
         "w": (T.LONG, [1, 2, 3, 4])}, num_partitions=1)


def _nlj(s, how):
    left, right = _left(s), _right(s)
    return left.join(right, on=left["a"] < right["k"], how=how)


def test_nlj_right_join_chunked():
    """Left side far above the pair budget: the right join streams left
    chunks and the pair space stays bounded (no n_l*n_r allocation)."""
    cpu = cpu_session(**SMALL_PAIRS)
    tpu = tpu_session(**SMALL_PAIRS)
    _assert_equal_rows(_nlj(cpu, "right").collect(),
                       _nlj(tpu, "right").collect())
    ops = _metric_ops(tpu, "nljChunks")
    assert ops, f"chunking did not fire: {tpu.last_metrics}"
    assert sum(tpu.last_metrics[op]["nljChunks"] for op in ops) >= 2


def test_nlj_full_join_chunked():
    cpu = cpu_session(**SMALL_PAIRS)
    tpu = tpu_session(**SMALL_PAIRS)
    _assert_equal_rows(_nlj(cpu, "full").collect(),
                       _nlj(tpu, "full").collect())
    assert _metric_ops(tpu, "nljChunks"), tpu.last_metrics


def test_nlj_right_join_no_matches_all_padded():
    """Right rows that match nothing across EVERY left chunk come back
    exactly once, left-NULL-padded."""
    def q(s):
        left = s.create_dataframe(
            {"a": (T.INT, list(range(3000)))}, num_partitions=2)
        right = s.create_dataframe(
            {"k": (T.INT, [-1, -2])}, num_partitions=1)
        return left.join(right, on=left["a"] < right["k"], how="right")

    cpu = cpu_session(**SMALL_PAIRS)
    tpu = tpu_session(**SMALL_PAIRS)
    _assert_equal_rows(q(cpu).collect(), q(tpu).collect())


def test_nlj_left_join_chunked():
    cpu = cpu_session(**SMALL_PAIRS)
    tpu = tpu_session(**SMALL_PAIRS)
    _assert_equal_rows(_nlj(cpu, "left").collect(),
                       _nlj(tpu, "left").collect())
    assert _metric_ops(tpu, "nljChunks"), tpu.last_metrics


def test_nlj_cross_join_chunked_with_strings():
    def q(s):
        left = s.create_dataframe(
            {"a": (T.INT, list(range(3000))),
             "s": (T.STRING, [f"row{i}" for i in range(3000)])},
            num_partitions=2)
        right = s.create_dataframe(
            {"w": (T.LONG, [1, 2, 3])}, num_partitions=1)
        return left.join(right, on=None, how="cross")

    cpu = cpu_session(**SMALL_PAIRS)
    tpu = tpu_session(**SMALL_PAIRS)
    _assert_equal_rows(q(cpu).collect(), q(tpu).collect())
    assert _metric_ops(tpu, "nljChunks"), tpu.last_metrics


def test_broadcast_build_side_registered_spillable():
    """The broadcast hash join's cached build side lives in the spill
    catalog (evictable), not as a pinned exec-node attribute."""
    from spark_rapids_tpu.ops.tpu_exec import TpuBroadcastHashJoinExec

    s = tpu_session()
    big = s.create_dataframe(
        {"a": (T.INT, [i % 5 for i in range(100)]),
         "v": (T.LONG, list(range(100)))}, num_partitions=2)
    small = s.create_dataframe(
        {"a": (T.INT, [0, 1, 2]), "w": (T.LONG, [7, 8, 9])},
        num_partitions=1)
    rows = big.join(small, on="a", how="inner").collect()
    assert len(rows) == 60

    found = []

    def walk(node):
        if isinstance(node, TpuBroadcastHashJoinExec):
            found.append(node)
        for c in getattr(node, "children", []):
            walk(c)

    walk(s.last_physical_plan)
    assert found, s.last_physical_plan.tree_string()
    cached = found[0]._bc_cache
    assert cached is not None
    h = cached[1]
    # registered with the catalog during the query, defer-closed when the
    # query ended: spillable while live, NOT leaked afterwards
    assert h is not None and h.closed
    again = big.join(small, on="a", how="inner").collect()
    assert len(again) == 60
