"""API-parity validation (api_validation/ApiValidation.scala analogue):
checks that every exec/expression family in the reference's component
inventory (SURVEY.md section 2.5) has a counterpart in this framework, so
parity gaps show up as test failures instead of silent omissions."""

import importlib

import pytest

# reference exec (SURVEY.md 2.5) -> implementing class here (TPU + CPU)
EXEC_PARITY = {
    "GpuProjectExec": ("spark_rapids_tpu.ops.tpu_exec", "TpuProjectExec"),
    "GpuFilterExec": ("spark_rapids_tpu.ops.tpu_exec", "TpuFilterExec"),
    "GpuUnionExec": ("spark_rapids_tpu.ops.tpu_exec", "TpuUnionExec"),
    "GpuRangeExec": ("spark_rapids_tpu.ops.tpu_exec", "TpuRangeExec"),
    "GpuHashAggregateExec": ("spark_rapids_tpu.ops.tpu_exec",
                             "TpuHashAggregateExec"),
    "GpuSortExec": ("spark_rapids_tpu.ops.tpu_exec", "TpuSortExec"),
    "GpuShuffledHashJoinExec": ("spark_rapids_tpu.ops.tpu_exec",
                                "TpuShuffledHashJoinExec"),
    "GpuBroadcastHashJoinExec": ("spark_rapids_tpu.ops.tpu_exec",
                                 "TpuBroadcastHashJoinExec"),
    "GpuBroadcastNestedLoopJoinExec": ("spark_rapids_tpu.ops.tpu_exec",
                                       "TpuNestedLoopJoinExec"),
    "GpuCartesianProductExec": ("spark_rapids_tpu.kernels.join",
                                "cross_join"),
    "GpuBroadcastExchangeExec": ("spark_rapids_tpu.parallel.exchange",
                                 "CpuBroadcastExchangeExec"),
    "GpuShuffleExchangeExec": ("spark_rapids_tpu.parallel.exchange",
                               "TpuShuffleExchangeExec"),
    "GpuHashPartitioning": ("spark_rapids_tpu.parallel.partitioning",
                            "HashPartitioning"),
    "GpuRangePartitioning": ("spark_rapids_tpu.parallel.partitioning",
                             "RangePartitioning"),
    "GpuRoundRobinPartitioning": ("spark_rapids_tpu.parallel.partitioning",
                                  "RoundRobinPartitioning"),
    "GpuSinglePartitioning": ("spark_rapids_tpu.parallel.partitioning",
                              "SinglePartitioning"),
    "GpuWindowExec": ("spark_rapids_tpu.ops.window", "TpuWindowExec"),
    "GpuExpandExec": ("spark_rapids_tpu.ops.tpu_exec", "TpuExpandExec"),
    "GpuLocalLimitExec": ("spark_rapids_tpu.ops.tpu_exec",
                          "TpuLocalLimitExec"),
    "GpuCoalesceBatches": ("spark_rapids_tpu.ops.tpu_exec",
                           "TpuCoalesceBatchesExec"),
    "GpuRowToColumnarExec": ("spark_rapids_tpu.plan.physical",
                             "HostToDeviceExec"),
    "GpuColumnarToRowExec": ("spark_rapids_tpu.plan.physical",
                             "DeviceToHostExec"),
    "GpuArrowEvalPythonExec": ("spark_rapids_tpu.exprs.python_udf",
                               "PandasUDF"),
    "GpuParquetScan": ("spark_rapids_tpu.io.scan", "CpuFileScanExec"),
    "GpuOverrides": ("spark_rapids_tpu.plan.overrides", "TpuOverrides"),
    "RapidsMeta": ("spark_rapids_tpu.plan.overrides", "PlanMeta"),
    "RapidsBufferCatalog": ("spark_rapids_tpu.mem.catalog", "BufferCatalog"),
    "SpillableColumnarBatch": ("spark_rapids_tpu.mem.catalog",
                               "SpillableBatch"),
    "GpuSemaphore": ("spark_rapids_tpu.runtime.device", "TpuSemaphore"),
    "GpuDeviceManager": ("spark_rapids_tpu.runtime.device", "DeviceRuntime"),
    "RapidsConf": ("spark_rapids_tpu.config", "RapidsConf"),
    "TableCompressionCodec": ("spark_rapids_tpu.mem.codec", "Codec"),
    "JCudfSerialization": ("spark_rapids_tpu.native_rt",
                           "serialize_host_batch"),
    "udf-compiler": ("spark_rapids_tpu.udf.compiler", "compile_udf"),
    "ColumnarRdd": ("spark_rapids_tpu.ml", "to_device_batches"),
    "UCXShuffleTransport": ("spark_rapids_tpu.parallel.mesh_shuffle",
                            "make_exchange_fn"),
    # fault tolerance: the reference's retry/OOM machinery
    # (RmmRapidsRetryIterator's withRetry + RetryOOM taxonomy) and the
    # task-retry delegation (SURVEY.md section 5) map to the unified
    # fault subsystem
    "RmmRapidsRetryIterator": ("spark_rapids_tpu.fault.retry",
                               "RetryPolicy"),
    "DeviceMemoryEventHandler": ("spark_rapids_tpu.mem.catalog",
                                 "run_with_oom_retry"),
    "TaskRetryLineage": ("spark_rapids_tpu.fault.recovery",
                         "run_partition_with_retry"),
}

# reference expression file (SURVEY.md 2.5 expression library) -> our module
EXPR_MODULE_PARITY = {
    "arithmetic.scala": "spark_rapids_tpu.exprs.arithmetic",
    "predicates.scala": "spark_rapids_tpu.exprs.predicates",
    "stringFunctions.scala": "spark_rapids_tpu.exprs.strings",
    "datetimeExpressions.scala": "spark_rapids_tpu.exprs.datetime",
    "AggregateFunctions.scala": "spark_rapids_tpu.exprs.aggregates",
    "mathExpressions.scala": "spark_rapids_tpu.exprs.mathexprs",
    "nullExpressions.scala": "spark_rapids_tpu.exprs.nullexprs",
    "conditionalExpressions.scala": "spark_rapids_tpu.exprs.conditional",
    "GpuCast": "spark_rapids_tpu.exprs.cast",
    "GpuWindowExpression": "spark_rapids_tpu.exprs.windows",
    "GpuRandomExpressions": "spark_rapids_tpu.exprs.misc",
    "GpuHashPartitioning-hash": "spark_rapids_tpu.exprs.hashing",
}


@pytest.mark.parametrize("ref", sorted(EXEC_PARITY.keys()))
def test_exec_parity(ref):
    mod_name, attr = EXEC_PARITY[ref]
    mod = importlib.import_module(mod_name)
    assert hasattr(mod, attr), f"{ref} has no counterpart {mod_name}.{attr}"


@pytest.mark.parametrize("ref", sorted(EXPR_MODULE_PARITY.keys()))
def test_expr_module_parity(ref):
    importlib.import_module(EXPR_MODULE_PARITY[ref])


def test_configs_docs_cover_full_registry():
    """docs/configs.md must include every registered conf — including ones
    defined in lazily-imported modules (catalog, multihost, python worker);
    a partial-registry regeneration silently drops rows."""
    import os

    import spark_rapids_tpu.config as C
    import spark_rapids_tpu.mem.catalog  # noqa: F401
    import spark_rapids_tpu.parallel.multihost  # noqa: F401
    import spark_rapids_tpu.runtime.python_worker  # noqa: F401
    import spark_rapids_tpu.session  # noqa: F401

    doc = open(os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "configs.md")).read()
    missing = [e.key for e in C.registry()
               if not e.internal and e.key not in doc]
    assert not missing, f"configs.md missing: {missing}"


def test_pyspark_dataframe_api_surface():
    """pyspark-API surface the frontend commits to (grows per round)."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.dataframe import DataFrame, GroupedData

    df_methods = [
        "select", "filter", "with_column", "with_column_renamed", "drop",
        "join", "cross_join", "union", "distinct", "drop_duplicates",
        "order_by", "limit", "sample", "repartition", "coalesce",
        "group_by", "rollup", "cube", "grouping_sets", "agg", "explode",
        "dropna", "fillna", "describe", "intersect", "subtract",
        "cache", "unpersist", "collect", "show", "head", "take",
        "to_pandas", "write_parquet", "write_csv", "write_orc",
        "create_or_replace_temp_view",
    ]
    for m in df_methods:
        assert hasattr(DataFrame, m), f"DataFrame.{m} missing"
    gd_methods = ["agg", "count", "sum", "avg", "min", "max", "pivot",
                  "apply_in_pandas", "agg_in_pandas", "cogroup"]
    for m in gd_methods:
        assert hasattr(GroupedData, m), f"GroupedData.{m} missing"
    fns = ["col", "lit", "sum", "count", "avg", "min", "max", "first",
           "last", "count_distinct", "percentile", "stddev",
           "stddev_pop", "variance", "var_pop", "corr", "covar_pop",
           "covar_samp", "hex", "grouping_id", "when",
           "coalesce", "concat", "substring", "substring_index", "split",
           "initcap", "upper", "lower", "regexp_replace", "broadcast",
           "row_number", "rank", "dense_rank", "lag", "lead", "hash",
           "year", "month", "dayofmonth", "weekday", "unix_timestamp",
           "udf", "pandas_udf"]
    for fn in fns:
        assert hasattr(F, fn), f"functions.{fn} missing"
