"""Join kernel tests vs a python oracle implementing SQL join semantics."""

import jax
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.batch import HostBatch, device_to_host, host_to_device
from spark_rapids_tpu.exprs.base import DevVal
from spark_rapids_tpu.kernels.join import cross_join, hash_join

from conftest import assert_batches_equal


def make_batch(pydict):
    return host_to_device(HostBatch.from_pydict(pydict))


def join_oracle(left, right, l_keys, r_keys, how):
    """Rows as dict-of-lists; returns joined dict-of-lists (unordered)."""
    lnames = list(left.keys())
    rnames = list(right.keys())
    ln = len(left[lnames[0]][1])
    rn = len(right[rnames[0]][1])

    def key(of, names, i):
        k = tuple(of[n][1][i] for n in names)
        return None if any(v is None for v in k) else k

    out = {n: [] for n in lnames + (rnames if how not in
                                    ("left_semi", "left_anti") else [])}
    l_matched = [False] * ln
    r_matched = [False] * rn
    for i in range(ln):
        ki = key(left, l_keys, i)
        for j in range(rn):
            if ki is not None and ki == key(right, r_keys, j):
                l_matched[i] = True
                r_matched[j] = True
                if how in ("inner", "left", "right", "full"):
                    for n in lnames:
                        out[n].append(left[n][1][i])
                    for n in rnames:
                        out[n].append(right[n][1][j])
    if how in ("left", "full"):
        for i in range(ln):
            if not l_matched[i]:
                for n in lnames:
                    out[n].append(left[n][1][i])
                for n in rnames:
                    out[n].append(None)
    if how in ("right", "full"):
        for j in range(rn):
            if not r_matched[j]:
                for n in lnames:
                    out[n].append(None)
                for n in rnames:
                    out[n].append(right[n][1][j])
    if how == "left_semi":
        for i in range(ln):
            if l_matched[i]:
                for n in lnames:
                    out[n].append(left[n][1][i])
    if how == "left_anti":
        for i in range(ln):
            if not l_matched[i]:
                for n in lnames:
                    out[n].append(left[n][1][i])
    return out


LEFT = {
    "k": (T.INT, [1, 2, 2, None, 5, 7]),
    "ks": (T.STRING, ["a", "b", "b", "c", None, "e"]),
    "lv": (T.DOUBLE, [0.5, 1.5, 2.5, 3.5, 4.5, None]),
}
RIGHT = {
    "rk": (T.INT, [2, 2, 1, 9, None, 5]),
    "rks": (T.STRING, ["b", "b", "a", "x", "c", None]),
    "rv": (T.LONG, [10, 20, 30, 40, None, 60]),
}


def out_schema(how):
    lf = [(n, LEFT[n][0]) for n in LEFT]
    rf = [(n, RIGHT[n][0]) for n in RIGHT]
    if how in ("left_semi", "left_anti"):
        return T.Schema(lf)
    return T.Schema(lf + rf)


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "left_semi", "left_anti"])
def test_hash_join_two_keys(how):
    lb = make_batch(LEFT)
    rb = make_batch(RIGHT)
    l_keys = [DevVal.from_column(lb.column("k")),
              DevVal.from_column(lb.column("ks"))]
    r_keys = [DevVal.from_column(rb.column("rk")),
              DevVal.from_column(rb.column("rks"))]
    got_b = hash_join(lb, l_keys, rb, r_keys, how, out_schema(how))
    got = device_to_host(got_b).to_pydict()
    exp = join_oracle(LEFT, RIGHT, ["k", "ks"], ["rk", "rks"], how)
    assert_batches_equal(exp, got, approx=True, ignore_order=True)


def test_inner_join_no_matches():
    lb = make_batch({"k": (T.INT, [1, 2, 3])})
    rb = make_batch({"rk": (T.INT, [7, 8, 9]), "rv": (T.INT, [1, 2, 3])})
    got_b = hash_join(
        lb, [DevVal.from_column(lb.column("k"))],
        rb, [DevVal.from_column(rb.column("rk"))], "inner",
        T.Schema([("k", T.INT), ("rk", T.INT), ("rv", T.INT)]))
    assert int(jax.device_get(got_b.num_rows)) == 0


def test_join_duplicate_heavy(rng):
    n = 300
    lk = [None if rng.rand() < 0.05 else int(rng.randint(0, 10))
          for _ in range(n)]
    rk = [None if rng.rand() < 0.05 else int(rng.randint(0, 10))
          for _ in range(180)]
    left = {"k": (T.INT, lk), "lv": (T.INT, list(range(n)))}
    right = {"rk": (T.INT, rk), "rv": (T.INT, list(range(180)))}
    lb, rb = make_batch(left), make_batch(right)
    for how in ("inner", "left", "full"):
        sch = T.Schema([("k", T.INT), ("lv", T.INT), ("rk", T.INT),
                        ("rv", T.INT)])
        got = device_to_host(hash_join(
            lb, [DevVal.from_column(lb.column("k"))],
            rb, [DevVal.from_column(rb.column("rk"))], how, sch)).to_pydict()
        exp = join_oracle(left, right, ["k"], ["rk"], how)
        assert_batches_equal(exp, got, ignore_order=True)


def test_cross_join():
    left = {"a": (T.INT, [1, 2, 3]), "s": (T.STRING, ["x", "yy", None])}
    right = {"b": (T.INT, [10, 20])}
    lb, rb = make_batch(left), make_batch(right)
    sch = T.Schema([("a", T.INT), ("s", T.STRING), ("b", T.INT)])
    got = device_to_host(cross_join(lb, rb, sch)).to_pydict()
    exp = {"a": [], "s": [], "b": []}
    for i in range(3):
        for j in range(2):
            exp["a"].append(left["a"][1][i])
            exp["s"].append(left["s"][1][i])
            exp["b"].append(right["b"][1][j])
    assert_batches_equal(exp, got, ignore_order=True)


def test_outer_join_string_caps_count_copied_bytes():
    # Regression: null-padded outer rows gather row 0's string bytes
    # (validity is masked after the copy), so byte caps sized over
    # `live & valid` undersized the output buffer and truncated the
    # LAST real string.  Caps must count what the gather copies.
    n = 64
    left = {"k": (T.INT, list(range(n))),
            "s": (T.STRING, ["pad-string-%02d" % i for i in range(n)])}
    right = {"rk": (T.INT, [1, 2, 999]),
             "rs": (T.STRING,
                    ["a-rather-long-anchor-string-0000", "b", "missing"])}
    lb, rb = make_batch(left), make_batch(right)
    sch = T.Schema([("k", T.INT), ("s", T.STRING),
                    ("rk", T.INT), ("rs", T.STRING)])
    got = device_to_host(hash_join(
        lb, [DevVal.from_column(lb.column("k"))],
        rb, [DevVal.from_column(rb.column("rk"))], "full", sch)).to_pydict()
    exp = join_oracle(left, right, ["k"], ["rk"], "full")
    assert_batches_equal(exp, got, ignore_order=True)
    assert "missing" in got["rs"]
