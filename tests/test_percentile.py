"""Exact-percentile aggregate: numpy ground truth + TPU/CPU engine
agreement (the reference exposes percentile through Spark SQL; mortgage
AggregatesWithPercentiles is its benchmark user)."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T

from compare import assert_tpu_cpu_equal, tpu_session

DATA = {
    "g": (T.STRING, ["a", "a", "a", "b", "b", "c", "c", "c", "c", "d"]),
    "x": (T.DOUBLE, [5.0, 1.0, 3.0, 10.0, 20.0, 2.0, None, 8.0, 4.0,
                     None]),
    "y": (T.INT, [7, 1, 5, 2, 4, 9, 3, 6, 8, 0]),
}


def _expected(p):
    """numpy linear interpolation == Spark exact percentile."""
    groups = {"a": [5.0, 1.0, 3.0], "b": [10.0, 20.0],
              "c": [2.0, 8.0, 4.0], "d": []}
    out = {}
    for g, vals in groups.items():
        out[g] = None if not vals else float(np.percentile(vals, p * 100))
    return out


@pytest.mark.parametrize("p", [0.0, 0.25, 0.5, 0.9, 1.0])
def test_percentile_ground_truth(p):
    s = tpu_session()
    df = s.create_dataframe(DATA, num_partitions=2)
    rows = (df.group_by("g")
            .agg(F.percentile("x", p).alias("pct"))
            .order_by("g").collect())
    exp = _expected(p)
    assert len(rows) == 4
    for g, v in rows:
        if exp[g] is None:
            assert v is None, f"group {g} at p={p}: {v}"
        else:
            assert v == pytest.approx(exp[g], rel=1e-6), f"group {g} p={p}"


def test_percentile_with_regular_aggs():
    def build(s):
        df = s.create_dataframe(DATA, num_partitions=3)
        return (df.group_by("g")
                .agg(F.percentile("x", 0.5).alias("med"),
                     F.sum("y").alias("sy"),
                     F.count("x").alias("cx"),
                     F.percentile("y", 0.75).alias("y75"))
                .order_by("g"))

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_percentile_sql_grouped():
    def build(s):
        df = s.create_dataframe(DATA, num_partitions=2)
        s.register_view("t", df)
        return s.sql(
            "SELECT g, percentile(x, 0.5) AS med FROM t "
            "GROUP BY g ORDER BY g")

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_percentile_sql_global_ungrouped():
    def build(s):
        df = s.create_dataframe(DATA, num_partitions=2)
        s.register_view("t", df)
        return s.sql("SELECT percentile(y, 0.25) AS q1 FROM t")

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)


def test_percentile_ignores_inf_outside_interpolation_ranks():
    """An inf in the group must not poison the sum (0 * inf = NaN): only
    the two interpolation ranks may contribute."""
    s = tpu_session()
    df = s.create_dataframe({
        "g": (T.STRING, ["a", "a", "a", "b", "b"]),
        "x": (T.DOUBLE, [1.0, 2.0, float("inf"), float("-inf"), 5.0]),
    }, num_partitions=2)
    rows = dict(df.group_by("g")
                .agg(F.percentile("x", 0.5).alias("med"))
                .order_by("g").collect())
    assert rows["a"] == pytest.approx(2.0)   # inf sorts last, untouched
    # b interpolates between -inf and 5.0 -> -inf (a rank the
    # interpolation genuinely touches may still produce an infinity)
    assert rows["b"] == float("-inf")


def test_percentile_sql_rejects_non_numeric_percentage():
    s = tpu_session()
    s.register_view("t", s.create_dataframe(DATA, num_partitions=1))
    with pytest.raises(SyntaxError):
        s.sql("SELECT percentile(x, 'abc') FROM t")


def test_percentile_rejects_bad_percentage():
    with pytest.raises(ValueError):
        F.percentile("x", 1.5)


def test_mortgage_percentiles_variant():
    from spark_rapids_tpu.benchmarks.mortgage_like import (
        aggregates_with_percentiles, register_mortgage,
    )

    def build(s):
        register_mortgage(s, sf=0.03, num_partitions=3)
        return aggregates_with_percentiles(s)

    assert_tpu_cpu_equal(build, approx=True, ignore_order=False)
