"""TPC-H-like query correctness: every query runs on the TPU engine and the
CPU engine and must agree (TpchLikeSparkSuite analogue)."""

import pytest

from spark_rapids_tpu.benchmarks.datagen import register_tpch
from spark_rapids_tpu.benchmarks.tpch_like import QUERIES

from compare import assert_tpu_cpu_equal

SF = 0.02


@pytest.mark.parametrize("qname", sorted(QUERIES.keys()))
def test_tpch_like_query(qname):
    def build(s):
        register_tpch(s, sf=SF, num_partitions=3)
        return s.sql(QUERIES[qname])
    ordered = "ORDER BY" in QUERIES[qname].upper()
    assert_tpu_cpu_equal(build, approx=True, ignore_order=not ordered)


def test_bench_utils_report(tmp_path):
    from compare import tpu_session
    from spark_rapids_tpu.benchmarks.bench_utils import run_bench
    s = tpu_session()
    register_tpch(s, sf=0.005, num_partitions=2)
    path = str(tmp_path / "report.json")
    rep = run_bench(s, "q6", lambda: s.sql(QUERIES["q6"]),
                    iterations=1, warmups=0, report_path=path)
    assert rep["result_rows"] >= 1
    import json
    with open(path) as f:
        assert json.load(f)["benchmark"] == "q6"
