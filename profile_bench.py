"""Stage-by-stage timing of the bench pipeline on the real device.

Plans are fingerprint-cached by the session, so repeated ``collect()``s of
structurally identical queries reuse compiled kernels — each labeled
timing below is steady-state, not compile time.
"""

import time

from bench import PARTS, ROWS, make_data
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.session import TpuSparkSession
from spark_rapids_tpu import functions as F


def t(label, fn, n=3):
    fn()  # warmup (compile once; later calls hit the plan+jit caches)
    best = float("inf")
    for _ in range(n):
        t0 = time.monotonic()
        fn()
        best = min(best, time.monotonic() - t0)
    print(f"{label:42s} {best*1000:9.1f} ms")
    return best


def main():
    data = make_data(ROWS)
    conf = RapidsConf({"spark.rapids.sql.enabled": True,
                       "spark.sql.shuffle.partitions": PARTS,
                       "spark.rapids.sql.variableFloatAgg.enabled": True})
    s = TpuSparkSession(conf)
    df = s.create_dataframe(data, num_partitions=PARTS).cache()

    t0 = time.monotonic()
    df.count()
    print(f"{'cache materialize + first count':42s} "
          f"{(time.monotonic()-t0)*1000:9.1f} ms")

    t("count (cached scan + keyless agg)", lambda: df.count())

    filt = df.filter((df["ss_quantity"] < 25) &
                     (df["ss_ext_discount_amt"] > 10.0))
    t("filter + count", lambda: filt.count())

    proj = filt.with_column(
        "revenue", df["ss_sales_price"] * df["ss_ext_discount_amt"])
    agg = proj.group_by("ss_item_sk").agg(
        F.sum("revenue").alias("sum_rev"),
        F.count("revenue").alias("cnt"),
        F.avg("ss_sales_price").alias("avg_price"))
    t("filter+proj+groupby agg collect", lambda: agg.collect())

    full = agg.order_by("ss_item_sk")
    t(".. + order_by collect (bench query)", lambda: full.collect())
    m = s.last_metrics
    print("pipeline metrics:", m.get("pipeline"), "| memory:",
          m.get("memory"))


if __name__ == "__main__":
    main()
