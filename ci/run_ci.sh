#!/usr/bin/env bash
# CI pipeline (SURVEY.md section 4.6 analogue of the reference's
# jenkins/ + github-actions workflows): unit + integration tests on the
# virtual 8-device CPU mesh, entry-point compile checks, multichip dryrun.
#
# Usage: ci/run_ci.sh [quick|full]
#   quick: kernel + expression + e2e suites only
#   full (default): whole suite + graft entry + 8-device dryrun
set -euo pipefail
cd "$(dirname "$0")/.."

MODE="${1:-full}"

echo "== python/jax versions"
python - << 'PY'
import sys, jax
print(sys.version.split()[0], "jax", jax.__version__)
PY

# Per-test wall clock bound (tests/conftest.py SIGALRM hook): a wedged
# test (e.g. a leaked read-ahead worker blocking the next suite) FAILS
# with a TimeoutError + traceback instead of hanging the whole run.
export PYTEST_PER_TEST_TIMEOUT="${PYTEST_PER_TEST_TIMEOUT:-120}"

echo "== docs/configs.md freshness"
python ci/gen_configs_doc.py --check

# Static analysis gate BEFORE any test runs: rapidslint is runtime-free
# (plain ast, no jax import) so the whole tree checks in ~2s — a lint
# regression fails the build without paying for a suite run first.
# Budget: must stay under 15s.  See docs/static_analysis.md.
echo "== rapidslint gate"
python tools/rapidslint.py --check

# Structural plan verification for every query the suite executes:
# schema/transition consistency, donation-mask provenance, semaphore
# balance (spark_rapids_tpu/analysis/plan_verify.py via tests/conftest.py).
export RAPIDS_PLAN_VERIFY=1

if [ "$MODE" = "quick" ]; then
  python -m pytest tests/test_kernels_layout.py tests/test_kernels_join.py \
      tests/test_exprs.py tests/test_e2e_basic.py -q
  exit 0
fi

echo "== full test suite"
python -m pytest tests/ -q

echo "== bench smoke (tiny rows, CPU backend): JSON must parse and carry"
echo "   the data-plane fields (donated_bytes / h2d_gb_per_sec / ...)"
BENCH_ROWS=4096 BENCH_PARTS=1 BENCH_PLATFORM=cpu BENCH_BACKEND_WAIT_SECS=120 \
BENCH_REPIN=1 python - << 'PY'
import json
import subprocess
import sys

out = subprocess.run([sys.executable, "bench.py"], capture_output=True,
                     text=True, timeout=600)
assert out.returncode == 0, f"bench.py failed:\n{out.stderr[-3000:]}"
lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
assert lines, f"no JSON line in bench output:\n{out.stdout[-2000:]}"
j = json.loads(lines[-1])
for key in ("value", "donated_bytes", "h2d_gb_per_sec", "d2h_gb_per_sec",
            "shuffle_gb_per_sec", "shuffle_split_dispatches",
            "shuffle_syncs", "async_partitions", "dispatch_count",
            "retry_count", "device_lost_count", "partition_fallbacks",
            "faults_injected", "spill_gb_per_sec", "spill_sync_gb_per_sec",
            "spill_async_speedup", "spill_queue_depth_max",
            "aqe_rows_per_sec", "aqe_speedup", "aqe_parity",
            "aqe_coalesced_partitions", "aqe_broadcast_switches",
            "aqe_skew_splits", "aqe_estimate_error_pct",
            "obs_event_count", "obs_overhead_pct",
            "serve_queries_per_sec", "serve_p50_ms", "serve_p99_ms",
            "serve_batched_queries", "serve_vs_serial", "serve_parity",
            "serve_second_session_compiles", "serve_tenants",
            "scan_gb_per_sec", "scan_decode_gb_per_sec",
            "scan_h2d_overlap_pct", "scan_chunks_skipped",
            "scan_v2_vs_v1", "readahead_depth_effective",
            "shuffle_wire_gb_per_sec", "shuffle_encoded_bytes_saved",
            "mesh_rows_per_sec_by_devices",
            "mesh_spmd_vs_hostdriven", "mesh_backend",
            "mesh_join_fused", "mesh_join_rows_per_sec_by_devices",
            "mesh_fallback_count",
            "pallas_kernels_enabled", "pallas_speedup_by_kernel",
            "pallas_fallback_count",
            "history_warm_speedup", "fragment_cache_hits",
            "telemetry_overhead_pct", "critpath_top_site",
            "regression_alerts",
            "frontend_queries_per_sec", "frontend_p50_ms",
            "frontend_p99_ms", "frontend_vs_serial", "frontend_parity",
            "frontend_second_client_compiles", "result_cache_hits",
            "admission_shed"):
    assert key in j, f"bench JSON missing {key}: {sorted(j)}"
assert isinstance(j["critpath_top_site"], str) and j["critpath_top_site"], j
assert isinstance(j["telemetry_overhead_pct"], float), j
assert isinstance(j["regression_alerts"], int) and \
    j["regression_alerts"] >= 0, j
assert j["value"] > 0, j
assert j["scan_gb_per_sec"] > 0, j
assert j["shuffle_encoded_bytes_saved"] >= 0, j
assert j["readahead_depth_effective"] >= 1, j
assert j["spill_gb_per_sec"] > 0, j
assert j["aqe_parity"] is True, j
assert j["aqe_coalesced_partitions"] > 0, j
assert j["serve_parity"] is True, j
assert j["serve_batched_queries"] > 0, j
assert j["serve_second_session_compiles"] == 0, j
assert j["frontend_parity"] is True, j
assert j["frontend_second_client_compiles"] == 0, j
assert j["result_cache_hits"] > 0, j
assert float(j["frontend_queries_per_sec"]) > 0, j
assert isinstance(j["mesh_rows_per_sec_by_devices"], dict), j
# fused-join lane gates: the shuffled hash join must actually compile
# into the fused program, with zero overflow/compat fallbacks at the
# default growth factor
assert j["mesh_join_fused"] >= 1, j
assert isinstance(j["mesh_join_rows_per_sec_by_devices"], dict), j
assert j["mesh_fallback_count"] == 0, j
assert j["fragment_cache_hits"] > 0, j
assert j["history_warm_speedup"] > 0, j
# pallas kernel-tier lane gates: all four kernels conf-enabled by
# default, every kernel measured, and on a non-TPU backend the
# default-conf probe must pay (and count) its fallbacks
assert sorted(j["pallas_kernels_enabled"]) == [
    "gatherScatter", "joinProbe", "stringHash", "strings"], j
assert isinstance(j["pallas_speedup_by_kernel"], dict) and \
    sorted(j["pallas_speedup_by_kernel"]) == [
        "gatherScatter", "joinProbe", "stringHash", "strings"], j
assert all(v > 0 for v in j["pallas_speedup_by_kernel"].values()), j
assert j["pallas_fallback_count"] >= 1, j
# fused-vs-host-driven ratio is recorded, NOT gated: CPU virtual devices
# emulate ICI through host collectives, so the ratio is informational
print("mesh spmd vs host-driven (informational):",
      j["mesh_spmd_vs_hostdriven"], "backend:", j["mesh_backend"],
      "curve:", j["mesh_rows_per_sec_by_devices"])
print("bench smoke ok:", {k: j[k] for k in (
    "value", "donated_bytes", "h2d_gb_per_sec", "d2h_gb_per_sec",
    "shuffle_gb_per_sec", "shuffle_split_dispatches", "shuffle_syncs",
    "async_partitions", "retry_count", "device_lost_count",
    "spill_gb_per_sec", "spill_sync_gb_per_sec")})
PY

echo "== serve smoke: rapidsserve with 2 weighted tenants and a per-query"
echo "   dispatch:oom@2 fault — every served query must recover with"
echo "   correct rows, latencies parseable, per-tenant counts consistent"
python - << 'PY'
import json
import subprocess
import sys

out = subprocess.run(
    [sys.executable, "tools/rapidsserve.py", "--tenants", "a:2,b:1",
     "--queries", "12", "--rows", "256", "--concurrency", "2",
     "--fault", "dispatch:oom@2"],
    capture_output=True, text=True, timeout=600)
assert out.returncode == 0, f"rapidsserve failed:\n{out.stderr[-3000:]}"
lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
assert lines, f"no JSON line in rapidsserve output:\n{out.stdout[-2000:]}"
j = json.loads(lines[-1])
assert j["serve_parity"] is True, j
assert j["serve_failed"] == 0, j
assert j["serve_faults_injected"] >= 1, j
assert float(j["serve_p99_ms"]) > 0, j
assert float(j["serve_p50_ms"]) <= float(j["serve_p99_ms"]), j
tenants = j["serve_tenants"]
assert set(tenants) == {"a", "b"}, tenants
assert tenants["a"]["weight"] == 2.0 and tenants["b"]["weight"] == 1.0, tenants
for name, t in tenants.items():
    assert t["submitted"] == t["completed"] + t["failed"], (name, t)
assert sum(t["completed"] for t in tenants.values()) == j["serve_completed"], j
print("serve smoke ok:", {k: j[k] for k in (
    "serve_queries_per_sec", "serve_p50_ms", "serve_p99_ms",
    "serve_batched_queries", "serve_faults_injected", "serve_retries",
    "serve_second_session_compiles")})
PY

echo "== front-door smoke: rapidsserve --server subprocess, 2 weighted"
echo "   tenants x concurrent socket clients — row parity vs in-process,"
echo "   second-client compileCount == 0, warm repeat served from the"
echo "   result cache, doomed deadline shed without executing, clean"
echo "   drain with held_depth == 0"
python - << 'PY'
import json
import select
import shutil
import signal
import subprocess
import sys
import tempfile
import threading

from spark_rapids_tpu.serve.bench import frontend_demo_session
from spark_rapids_tpu.serve.scheduler import DeadlineExceeded
from spark_rapids_tpu.serve.protocol import FrontDoorClient

hist_dir = tempfile.mkdtemp(prefix="rapids_frontdoor_smoke_")
proc = subprocess.Popen(
    [sys.executable, "tools/rapidsserve.py", "--server", "--port", "0",
     "--tenants", "a:2,b:1", "--concurrency", "2", "--rows", "512",
     "--history-dir", hist_dir],
    stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
try:
    # the banner is the FIRST stdout line; session build takes a while
    ready, _, _ = select.select([proc.stdout], [], [], 300)
    assert ready, "server printed no banner within 300s"
    banner = json.loads(proc.stdout.readline())
    host, port, sqls = banner["host"], banner["port"], banner["sqls"]

    def rows_of(batch):
        cols = batch.to_pydict()
        return sorted(zip(*[cols[n] for n in batch.schema.names]))

    # in-process oracle: same deterministic demo view, same SQL texts
    oracle = frontend_demo_session({"a": 2.0, "b": 1.0}, rows=512)
    want = {sql: rows_of(oracle.execute(oracle.sql(sql).plan))
            for sql in sqls}

    # warm passes bypassing the result cache: compile once AND seed the
    # admission predictor's history baseline (minRuns real executions)
    with FrontDoorClient(host, port) as c:
        for _ in range(3):
            for sql in sqls:
                batch, _m = c.submit_sql(sql, tenant="a", cache=False)
                assert rows_of(batch) == want[sql], sql

    # concurrent storm: one socket client per weighted tenant
    errs = []
    def storm(tenant):
        try:
            with FrontDoorClient(host, port) as c:
                for sql in sqls:
                    batch, _m = c.submit_sql(sql, tenant=tenant)
                    assert rows_of(batch) == want[sql], (tenant, sql)
        except Exception as e:  # surfaced below; threads must not die silently
            errs.append((tenant, repr(e)))
    threads = [threading.Thread(target=storm, args=(t,)) for t in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    assert not errs, errs

    with FrontDoorClient(host, port) as c:
        # a brand-new connection is a "second client": the prepared-
        # statement + shared plan caches must hand it warm executables
        batch, m = c.submit_sql(sqls[0], tenant="b", cache=False)
        assert rows_of(batch) == want[sqls[0]]
        assert m.get("compileCount", 0) == 0, m
        # warm repeat: served from the result cache, no dispatch at all
        batch, m = c.submit_sql(sqls[0], tenant="b")
        assert rows_of(batch) == want[sqls[0]]
        assert m.get("resultCacheHits", 0) > 0, m
        assert m.get("dispatchCount", 0) == 0, m
        # doomed deadline: the admission predictor sheds it fail-fast
        try:
            c.submit_sql(sqls[1], tenant="a", deadline_sec=1e-6,
                         cache=False)
            raise AssertionError("doomed deadline was not shed")
        except DeadlineExceeded:
            pass
        st = c.stats()
        assert st["frontend"]["admission_shed"] >= 1, st["frontend"]
        assert st["frontend"]["result_cache_hits"] >= 1, st["frontend"]
        assert st["scheduler"]["tenants"]["a"]["completed"] >= 1, st
        assert st["scheduler"]["tenants"]["b"]["completed"] >= 1, st
        d = c.drain()
        assert d["drained"] is True, d
        assert d["held_depth"] == 0, d
        print("front-door smoke ok:", {
            "port": port, "queries": 3 * len(sqls) + 2 * len(sqls) + 2,
            "admission_shed": st["frontend"]["admission_shed"],
            "result_cache_hits": st["frontend"]["result_cache_hits"]})
finally:
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(30)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(30)
    shutil.rmtree(hist_dir, ignore_errors=True)
assert proc.returncode == 0, (proc.returncode, proc.stderr.read()[-3000:])
PY

echo "== obs smoke: event log -> rapidsprof report + Perfetto-loadable trace"
python - << 'PY'
import json
import os
import subprocess
import sys
import tempfile

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.session import TpuSparkSession

log_dir = tempfile.mkdtemp(prefix="rapids_obs_smoke_")
s = TpuSparkSession(RapidsConf({
    "spark.rapids.sql.enabled": True,
    "spark.rapids.sql.tpu.obs.eventLogDir": log_dir,
}))
df = s.create_dataframe(
    {"k": [i % 7 for i in range(4096)], "v": list(range(4096))},
    num_partitions=2)
df.group_by("k").sum("v").order_by("k").collect()
assert s.last_metrics["obsEventCount"] > 0, s.last_metrics
assert s.query_history(), "no profile recorded"
logs = [os.path.join(log_dir, f) for f in os.listdir(log_dir)]
assert len(logs) == 1, logs

trace = os.path.join(log_dir, "trace.json")
out = subprocess.run(
    [sys.executable, "tools/rapidsprof.py", logs[0], "--chrome", trace],
    capture_output=True, text=True, timeout=300)
assert out.returncode == 0, f"rapidsprof failed:\n{out.stderr[-2000:]}"
assert "Exec" in out.stdout, f"report names no operator:\n{out.stdout}"
with open(trace) as f:
    tdoc = json.load(f)
assert tdoc["traceEvents"], "empty Chrome trace"
print("obs smoke ok:", {
    "events": s.last_metrics["obsEventCount"],
    "dropped": s.last_metrics["obsEventsDropped"],
    "trace_events": len(tdoc["traceEvents"])})
PY

echo "== telemetry smoke: flushed JSONL -> rapidstop --once renders >=1"
echo "   interval with nonzero dispatch wall, Prometheus export parses"
python - << 'PY'
import os
import subprocess
import sys
import tempfile
import time

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.session import TpuSparkSession

log_dir = tempfile.mkdtemp(prefix="rapids_telemetry_smoke_")
s = TpuSparkSession(RapidsConf({
    "spark.rapids.sql.enabled": True,
    "spark.rapids.sql.tpu.obs.eventLogDir": log_dir,
    "spark.rapids.sql.tpu.obs.telemetry.intervalMs": 25,
}))
df = s.create_dataframe(
    {"k": [i % 7 for i in range(8192)], "v": list(range(8192))},
    num_partitions=2)
q = df.group_by("k").sum("v")
q.collect()
time.sleep(0.06)  # let the open interval's window pass
q.collect()       # the flush at query end writes the completed intervals
assert s.last_metrics["telemetryIntervals"] >= 1, s.last_metrics
tpath = os.path.join(log_dir, f"telemetry-{os.getpid()}.jsonl")
assert os.path.exists(tpath), os.listdir(log_dir)

out = subprocess.run(
    [sys.executable, "tools/rapidstop.py", tpath, "--once"],
    capture_output=True, text=True, timeout=300)
assert out.returncode == 0, f"rapidstop failed:\n{out.stdout}{out.stderr}"
assert "telemetry:" in out.stdout, out.stdout
assert "dispatch" in out.stdout, out.stdout

prom = subprocess.run(
    [sys.executable, "tools/rapidstop.py", tpath, "--prom"],
    capture_output=True, text=True, timeout=300)
assert prom.returncode == 0, prom.stderr
wall = 0
for line in prom.stdout.strip().splitlines():
    if line.startswith("#"):
        assert line.split()[1] == "TYPE", line
        continue
    name, val = line.rsplit(" ", 1)
    float(val)  # every sample parses
    if name == 'rapids_site_wall_ns_total{site="dispatch"}':
        wall = float(val)
assert wall > 0, f"no dispatch wall in Prometheus export:\n{prom.stdout}"
print("telemetry smoke ok:", {
    "intervals": s.last_metrics["telemetryIntervals"],
    "dispatch_wall_ms": round(wall / 1e6, 2)})
PY

echo "== sentinel smoke: injected dispatch:slow regression must flag"
echo "   regressionAlerts > 0 against a clean baseline; a clean repeat"
echo "   must flag none; aggregates visible via rapidshist --json"
python - << 'PY'
import json
import shutil
import subprocess
import sys
import tempfile

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.session import TpuSparkSession

hist_dir = tempfile.mkdtemp(prefix="rapids_sentinel_smoke_")
try:
    s = TpuSparkSession(RapidsConf({
        "spark.rapids.sql.enabled": True,
        "spark.rapids.sql.tpu.history.dir": hist_dir,
        # re-execute warm repeats so the injected fault actually fires,
        # and keep the plan fingerprint identical run over run
        "spark.rapids.sql.tpu.history.fragments.enabled": False,
        "spark.rapids.sql.tpu.history.seed.enabled": False,
        # preset so toggling the spec off restores this exact conf
        # state and the clean repeat reuses the cached plan (an absent->
        # empty transition would replan and recompile, inflating wall)
        "spark.rapids.sql.tpu.faults.spec": "",
    }))
    df = s.create_dataframe(
        {"k": [i % 7 for i in range(4096)], "v": list(range(4096))},
        num_partitions=2)
    q = df.group_by("k").sum("v")
    for _ in range(4):
        q.collect()
        assert s.last_metrics["regressionAlerts"] == 0, s.last_metrics
    # faults. confs are excluded from the conf signature: the slow run
    # is judged against the clean baseline it just built
    s.conf.set("spark.rapids.sql.tpu.faults.spec",
               "dispatch:slow=500ms@1+")
    q.collect()
    m = dict(s.last_metrics)
    assert m["faultsInjected"] >= 1, m
    assert m["regressionAlerts"] > 0, m
    s.conf.set("spark.rapids.sql.tpu.faults.spec", "")
    q.collect()
    assert s.last_metrics["regressionAlerts"] == 0, s.last_metrics

    out = subprocess.run(
        [sys.executable, "tools/rapidshist.py", hist_dir, "--json"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    recs = json.loads(out.stdout)
    aggs = [r["agg"] for r in recs.values() if r.get("agg")]
    assert aggs and aggs[0]["n"] >= 4, recs
    assert "median" in aggs[0]["keys"]["wall_ns"], aggs[0]
    print("sentinel smoke ok:", {
        "alerts": m["regressionAlerts"],
        "baseline_runs": aggs[0]["n"],
        "wall_median_ms": round(
            aggs[0]["keys"]["wall_ns"]["median"] / 1e6, 2)})
finally:
    shutil.rmtree(hist_dir, ignore_errors=True)
PY

echo "== history smoke: same aggregation twice against a fresh history"
echo "   dir — the repeat must serve from the fragment cache (hits > 0,"
echo "   zero compiles, zero dispatches) with bit-identical rows, and the"
echo "   statistics store must be inspectable with rapidshist"
python - << 'PY'
import os
import shutil
import subprocess
import sys
import tempfile

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.history.fragcache import fragment_cache
from spark_rapids_tpu.session import TpuSparkSession

hist_dir = tempfile.mkdtemp(prefix="rapids_hist_smoke_")
try:
    fragment_cache().clear()
    s = TpuSparkSession(RapidsConf({
        "spark.rapids.sql.enabled": True,
        "spark.rapids.sql.tpu.history.dir": hist_dir,
    }))
    df = s.create_dataframe(
        {"k": [i % 7 for i in range(4096)], "v": list(range(4096))},
        num_partitions=2)
    q = df.group_by("k").sum("v")
    want = sorted(q.collect())
    m1 = dict(s.last_metrics)
    got = sorted(q.collect())
    m2 = dict(s.last_metrics)
    assert got == want, f"warm run diverged:\n{got[:5]}\n{want[:5]}"
    assert m2["fragmentCacheHits"] > 0, m2
    assert m2["compileCount"] == 0, m2
    assert m2["dispatchCount"] == 0, m2
    assert os.path.exists(os.path.join(hist_dir, "stats.jsonl")), \
        os.listdir(hist_dir)
    out = subprocess.run(
        [sys.executable, "tools/rapidshist.py", hist_dir],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, f"rapidshist failed:\n{out.stderr[-2000:]}"
    assert "fingerprint" in out.stdout, out.stdout
    print("history smoke ok:", {
        "cold_compiles": m1["compileCount"],
        "warm_hits": m2["fragmentCacheHits"],
        "warm_compiles": m2["compileCount"],
        "warm_dispatches": m2["dispatchCount"],
        "store_queries": m1["statsStoreQueries"]})
finally:
    fragment_cache().clear()
    shutil.rmtree(hist_dir, ignore_errors=True)
PY

echo "== fault-injection smoke: dispatch:oom@2 must spill-retry and still"
echo "   produce correct results with retryCount > 0"
python - << 'PY'
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.session import TpuSparkSession

def make(s):
    df = s.create_dataframe(
        {"k": [i % 7 for i in range(4096)],
         "v": list(range(4096))}, num_partitions=2)
    return df.group_by("k").sum("v")

clean = TpuSparkSession(RapidsConf({"spark.rapids.sql.enabled": True}))
want = sorted(make(clean).collect())

s = TpuSparkSession(RapidsConf({
    "spark.rapids.sql.enabled": True,
    "spark.rapids.sql.tpu.faults.spec": "dispatch:oom@2",
}))
got = sorted(make(s).collect())
assert got == want, f"faulted run diverged:\n{got[:5]}\n{want[:5]}"
m = s.last_metrics
assert m["retryCount"] > 0, m
assert m["faultsInjected"] >= 1, m
print("fault smoke ok:", {k: m[k] for k in (
    "retryCount", "faultsInjected", "deviceLostCount",
    "partitionFallbackCount", "backoffWallNs")})
PY

echo "== fault-injection smoke: exchange:oom@2 must replay the coalesced"
echo "   shuffle split through the retry ladder (split v2 path)"
python - << 'PY'
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.session import TpuSparkSession

def make(s):
    df = s.create_dataframe(
        {"k": [i % 7 for i in range(4096)],
         "v": list(range(4096))}, num_partitions=2)
    # two non-collapsed exchanges (hash groupby + range order_by): the
    # @2 rule fires on the SECOND exchange-site call of the query
    return df.group_by("k").sum("v").order_by("k")

clean = TpuSparkSession(RapidsConf({
    "spark.rapids.sql.enabled": True,
    "spark.rapids.sql.tpu.exchange.collapseLocal": False,
}))
want = make(clean).collect()

s = TpuSparkSession(RapidsConf({
    "spark.rapids.sql.enabled": True,
    "spark.rapids.sql.tpu.exchange.collapseLocal": False,
    "spark.rapids.sql.tpu.faults.spec": "exchange:oom@2",
}))
got = make(s).collect()
assert got == want, f"faulted run diverged:\n{got[:5]}\n{want[:5]}"
m = s.last_metrics
assert m["retryCount"] > 0, m
assert m["faultsInjected"] >= 1, m
assert m["shuffleSyncs"] >= 1, m
print("exchange fault smoke ok:", {k: m[k] for k in (
    "retryCount", "faultsInjected", "shuffleSyncs",
    "shuffleSplitDispatches", "shufflePieces")})
PY

echo "== fault-injection smoke: mesh:device_lost@1 through a FUSED mesh"
echo "   join program — the lost device replays the whole fused stage"
echo "   bit-identically with retryCount > 0 and held_depth == 0"
python - << 'PY'
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.session import TpuSparkSession

def make(s):
    left = s.create_dataframe(
        {"k": [i % 13 for i in range(4096)],
         "v": list(range(4096))}, num_partitions=4)
    right = s.create_dataframe(
        {"k": list(range(13)), "w": [i * 7 for i in range(13)]},
        num_partitions=2)
    return left.join(right, on="k", how="inner")

BASE = {
    "spark.rapids.sql.enabled": True,
    "spark.rapids.shuffle.ici.enabled": True,
    # threshold 0 keeps the shuffled-hash strategy: the join fuses INTO
    # the mesh shard_map program (mesh.spmd.enabled is default-on)
    "spark.sql.autoBroadcastJoinThreshold": 0,
}
clean = TpuSparkSession(RapidsConf(BASE))
want = sorted(map(str, make(clean).collect()))
assert clean.last_metrics["meshJoinsFused"] >= 1, clean.last_metrics

s = TpuSparkSession(RapidsConf({
    **BASE, "spark.rapids.sql.tpu.faults.spec": "mesh:device_lost@1"}))
got = sorted(map(str, make(s).collect()))
assert got == want, f"faulted fused join diverged:\n{got[:5]}\n{want[:5]}"
m = s.last_metrics
assert m["faultsInjected"] >= 1, m
assert m["deviceLostCount"] >= 1, m
assert m["retryCount"] > 0, m
assert m["meshJoinsFused"] >= 1, m
assert s.runtime.semaphore.held_depth() == 0
print("mesh fused-join fault smoke ok:", {k: m[k] for k in (
    "retryCount", "faultsInjected", "deviceLostCount",
    "meshJoinsFused", "meshProgramDispatches")})
PY

echo "== pallas kernel-tier smoke (interpret mode): one query per kernel"
echo "   family with the kernel forced on, bit-identical rows vs the"
echo "   kernel-off XLA run, zero fallbacks and held_depth == 0; plus the"
echo "   mesh fused join with the probe kernel on keeps shuffleSyncs == 0"
python - << 'PY'
import os

# same virtual-device trick as tests/conftest.py: the mesh leg below
# needs a multi-device mesh even on a single-CPU host
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.session import TpuSparkSession

PALLAS_ON = {
    "spark.rapids.sql.tpu.pallas.strings.enabled": True,
    "spark.rapids.sql.tpu.pallas.gatherScatter.enabled": True,
    "spark.rapids.sql.tpu.pallas.joinProbe.enabled": True,
    "spark.rapids.sql.tpu.pallas.stringHash.enabled": True,
    "spark.rapids.sql.tpu.pallas.interpret": True,
}
PALLAS_OFF = {k: False for k in PALLAS_ON}
BASE = {
    "spark.rapids.sql.enabled": True,
    "spark.sql.autoBroadcastJoinThreshold": 0,
}
NAMES = ["ace", "bog", "cab", "dim", "", "abacus", "zebra", "cabal"]
LEFT = {"name": [NAMES[i % len(NAMES)] for i in range(4096)],
        "v": list(range(4096))}
RIGHT = {"name": list(dict.fromkeys(NAMES)),
         "w": [i * 7 for i in range(len(dict.fromkeys(NAMES)))]}

def run(s):
    # string-key join (joinProbe + stringHash), contains filter
    # (strings), multi-partition concat on collect (gatherScatter)
    left = s.create_dataframe(LEFT, num_partitions=4)
    right = s.create_dataframe(RIGHT, num_partitions=2)
    df = left.join(right, on="name", how="inner")
    return sorted(map(str, df.filter(df["name"].contains("ab")).collect()))

off = TpuSparkSession(RapidsConf({**BASE, **PALLAS_OFF}))
want = run(off)
assert want, "smoke query returned no rows"

on = TpuSparkSession(RapidsConf({**BASE, **PALLAS_ON}))
got = run(on)
assert got == want, f"pallas parity diverged:\n{got[:5]}\n{want[:5]}"
m = on.last_metrics
# interpret mode engages every kernel: nothing may have fallen back
assert m["pallasFallbackCount"] == 0, m
assert on.runtime.semaphore.held_depth() == 0

# mesh fused join with the probe kernel on: the join still compiles
# INTO the fused shard_map program — no host-driven shuffle syncs
mesh = TpuSparkSession(RapidsConf({
    **BASE, **PALLAS_ON, "spark.rapids.shuffle.ici.enabled": True}))
got_mesh = run(mesh)
assert got_mesh == want, \
    f"mesh+pallas parity diverged:\n{got_mesh[:5]}\n{want[:5]}"
mm = mesh.last_metrics
assert mm["meshJoinsFused"] >= 1, mm
assert mm["shuffleSyncs"] == 0, mm
assert mm["pallasFallbackCount"] == 0, mm
assert mesh.runtime.semaphore.held_depth() == 0
print("pallas kernel-tier smoke ok:", {
    "rows": len(got), "pallasFallbackCount": m["pallasFallbackCount"],
    "meshJoinsFused": mm["meshJoinsFused"],
    "shuffleSyncs": mm["shuffleSyncs"]})
PY

echo "== adaptive smoke: skewed join coalesces with bit-identical rows"
echo "   adaptive on/off, and exchange:oom@2 replays through a"
echo "   coalesced-then-switched plan"
python - << 'PY'
import numpy as np
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.session import TpuSparkSession

rng = np.random.RandomState(11)
n = 20000
FACT = {"k": np.where(rng.rand(n) < 0.9, 0,
                      rng.randint(1, 50, n)).tolist(),
        "v": list(range(n))}
DIM = {"k": list(range(50)), "w": [i * 3 for i in range(50)]}
BASE = {
    "spark.rapids.sql.enabled": True,
    "spark.sql.shuffle.partitions": 8,
    "spark.rapids.sql.tpu.exchange.collapseLocal": False,
    "spark.sql.autoBroadcastJoinThreshold": -1,
}

def skew_join(s):
    big = s.create_dataframe(FACT, num_partitions=3)
    dim = s.create_dataframe(DIM, num_partitions=2)
    return sorted(map(str, big.join(dim, on="k").collect()))

on = TpuSparkSession(RapidsConf(BASE))
got_on = skew_join(on)
off = TpuSparkSession(RapidsConf({
    **BASE, "spark.rapids.sql.tpu.adaptive.enabled": False}))
got_off = skew_join(off)
assert got_on == got_off, "adaptive on/off rows diverged"
m = on.last_metrics
assert m["aqeCoalescedPartitions"] > 0, m
assert off.last_metrics["aqeCoalescedPartitions"] == 0, off.last_metrics
print("adaptive skew smoke ok:", {k: m[k] for k in (
    "aqeCoalescedPartitions", "aqeSkewSplits", "aqeStatsBytes")})

# coalesced-then-switched plan under an exchange OOM: aggregate join
# inputs (sizes unknown at plan time) with a live broadcast threshold;
# the @2 rule fires on the second exchange-site call mid-replan
def replan_join(s):
    big = s.create_dataframe(FACT, num_partitions=3) \
        .group_by("k").sum("v")
    dim = s.create_dataframe(DIM, num_partitions=2) \
        .group_by("k").sum("w")
    return sorted(map(str, big.join(dim, on="k").collect()))

REPLAN = {k: v for k, v in BASE.items()
          if k != "spark.sql.autoBroadcastJoinThreshold"}
clean = TpuSparkSession(RapidsConf(REPLAN))
want = replan_join(clean)
assert clean.last_metrics["aqeBroadcastSwitches"] >= 1, clean.last_metrics

s = TpuSparkSession(RapidsConf({
    **REPLAN, "spark.rapids.sql.tpu.faults.spec": "exchange:oom@2"}))
got = replan_join(s)
assert got == want, f"faulted replan diverged:\n{got[:3]}\n{want[:3]}"
m = s.last_metrics
assert m["retryCount"] > 0, m
assert m["faultsInjected"] >= 1, m
assert m["aqeBroadcastSwitches"] >= 1, m
print("adaptive fault smoke ok:", {k: m[k] for k in (
    "retryCount", "faultsInjected", "aqeBroadcastSwitches",
    "aqeCoalescedPartitions")})
PY

echo "== fault-injection smoke: scan:oom@2 through the adaptive read-ahead"
echo "   path — the faulted chunk replays through the retry ladder with"
echo "   bit-identical rows, dict columns intact, held_depth == 0"
python - << 'PY'
import os
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.session import TpuSparkSession

out = tempfile.mkdtemp(prefix="rapids_scan_fault_smoke_")
rng = np.random.RandomState(3)
n = 8192
cats = np.array([f"c{i:03d}" for i in range(64)], dtype=object)
pq.write_table(pa.table({
    "k": pa.array(rng.randint(0, 64, n).astype(np.int64)),
    "s": pa.array(cats[rng.randint(0, 64, n)]),
    "v": pa.array((rng.rand(n) * 10).round(3)),
}), os.path.join(out, "part-00000.parquet"), row_group_size=n // 8)

BASE = {
    "spark.rapids.sql.enabled": True,
    "spark.rapids.sql.tpu.scan.v2.enabled": True,
    "spark.rapids.sql.variableFloatAgg.enabled": True,
    # adaptive controller live (no explicit depth -> adaptive governs)
    "spark.rapids.sql.tpu.scan.readAhead.adaptive.enabled": True,
}

def q(s):
    from spark_rapids_tpu import functions as F
    df = s.read.parquet(out)
    return sorted(map(str, df.filter(df["k"] < 48).group_by("s")
                      .agg(F.sum("v").alias("sv"),
                           F.count("k").alias("c")).collect()))

clean = TpuSparkSession(RapidsConf(BASE))
want = q(clean)

s = TpuSparkSession(RapidsConf({
    **BASE, "spark.rapids.sql.tpu.faults.spec": "scan:oom@2"}))
got = q(s)
assert got == want, f"faulted scan diverged:\n{got[:3]}\n{want[:3]}"
m = s.last_metrics
assert m["retryCount"] > 0, m
assert m["faultsInjected"] >= 1, m
assert s.runtime.semaphore.held_depth() == 0
print("scan fault smoke ok:", {k: m[k] for k in (
    "retryCount", "faultsInjected", "scanBytesDecoded",
    "scanDictColumns")})
PY

echo "== fault-injection smoke: unspill:oom@1 under a tiny budget must"
echo "   hit the rehydration path, retry, and still produce exact results"
python - << 'PY'
import numpy as np
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.runtime.device import DeviceRuntime
from spark_rapids_tpu.session import TpuSparkSession

def make(s):
    n = 20000
    rng = np.random.RandomState(5)
    left = s.create_dataframe(
        {"k": rng.randint(0, 500, n).tolist(),
         "v": rng.randint(0, 100, n).tolist()}, num_partitions=3)
    right = s.create_dataframe(
        {"k": list(range(500)), "w": list(range(500))}, num_partitions=2)
    return left.join(right, on="k", how="inner")

BASE = {
    "spark.rapids.sql.enabled": True,
    "spark.sql.shuffle.partitions": 4,
    "spark.rapids.sql.tpu.exchange.collapseLocal": False,
    "spark.sql.autoBroadcastJoinThreshold": -1,
}
DeviceRuntime.reset()
try:
    clean = TpuSparkSession(RapidsConf(BASE))
    want = sorted(map(str, make(clean).collect()))
    DeviceRuntime.reset()
    s = TpuSparkSession(RapidsConf({
        **BASE,
        # ~64KB budget: shuffle pieces spill, so their reads must unspill
        "spark.rapids.memory.tpu.spillBudgetBytes": 65536,
        "spark.rapids.sql.tpu.faults.spec": "unspill:oom@1",
    }))
    got = sorted(map(str, make(s).collect()))
    assert got == want, f"faulted run diverged:\n{got[:5]}\n{want[:5]}"
    m = s.last_metrics
    assert m["faultsInjected"] >= 1, m
    assert m["retryCount"] > 0, m
    mem = m.get("memory", {})
    assert mem.get("unspilled", 0) > 0, mem
    print("unspill fault smoke ok:", {k: m[k] for k in (
        "retryCount", "faultsInjected", "unspillPrefetchHits")},
        {k: mem.get(k, 0) for k in ("spilled_to_host", "unspilled")})
finally:
    DeviceRuntime.reset()
PY

echo "== oocore smoke: q1 under a 2MB budget, async writer on AND off,"
echo "   both bit-correct with spills recorded"
python - << 'PY'
import tempfile, os
from spark_rapids_tpu.benchmarks import oocore_run

for async_on in (True, False):
    out = os.path.join(tempfile.mkdtemp(), "oocore.md")
    res = oocore_run.run(
        sf=0.2, budget_mb=2, queries=["q1"], out_path=out,
        extra_conf={"spark.rapids.sql.tpu.spill.async.enabled": async_on})
    r = res["q1"]
    assert r["agree"], (async_on, r)
    assert r["spilled_to_host"] + r["spilled_to_disk"] > 0, (async_on, r)
    print(f"oocore q1 async={async_on}: tpu {r['tpu_s']}s "
          f"spills {r['spilled_to_host']}/{r['spilled_to_disk']} "
          f"unspilled {r['unspilled']}")
PY

echo "== single-chip entry compile check"
python - << 'PY'
import jax
import __graft_entry__ as g
fn, args = g.entry()
out = jax.jit(fn)(*args)
print("entry ok:", [getattr(o, "shape", o) for o in out[:2]])
PY

echo "== 8-device multichip dryrun (virtual CPU mesh)"
python - << 'PY'
import __graft_entry__ as g
g.dryrun_multichip(8)
print("dryrun ok")
PY

echo "== two-process multi-host dryrun (2 x 4 virtual CPU devices)"
python -m pytest tests/test_multihost.py -q

echo "CI PASSED"
