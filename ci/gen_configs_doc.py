#!/usr/bin/env python
"""Regenerate docs/configs.md from the conf registry (the reference's
``RapidsConf.main`` doc generator, RapidsConf.scala:717,814) — or, with
``--check``, fail loudly when the committed doc is stale.

Confs registered by lazily-imported modules (spill catalog, multihost,
python worker, session) must be imported FIRST or their rows silently
drop out of the doc — the same import list
tests/test_api_parity.py::test_configs_docs_cover_full_registry uses.

Usage:  python ci/gen_configs_doc.py [--check]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def full_registry_docs() -> str:
    import spark_rapids_tpu.config as C
    import spark_rapids_tpu.mem.catalog  # noqa: F401
    import spark_rapids_tpu.parallel.multihost  # noqa: F401
    import spark_rapids_tpu.runtime.python_worker  # noqa: F401
    import spark_rapids_tpu.session  # noqa: F401
    return C.generate_docs()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if docs/configs.md is stale (CI gate)")
    args = ap.parse_args(argv)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "configs.md")
    doc = full_registry_docs()
    if args.check:
        on_disk = open(path).read() if os.path.exists(path) else ""
        if on_disk != doc:
            sys.stderr.write(
                "docs/configs.md is STALE — regenerate with "
                "`python ci/gen_configs_doc.py`\n")
            return 1
        print("docs/configs.md is up to date")
        return 0
    with open(path, "w") as f:
        f.write(doc)
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
