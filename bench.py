"""Benchmark driver: TPC-DS q6-style pipeline (scan -> filter -> project ->
hash aggregate -> sort) through the full engine, TPU plan vs CPU fallback
plan (the Spark-CPU stand-in).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
value = TPU rows/sec through the pipeline; vs_baseline = TPU throughput /
CPU-engine throughput (the reference's own headline is 3-7x vs Spark CPU,
docs/FAQ.md:60-66 — BASELINE.md).  Extra keys on the same line:
  vs_pandas_cpu    — TPU throughput / pandas (C groupby) throughput, an
                     engine-independent CPU baseline.  pyspark itself is
                     not installable in this zero-egress image, so pandas
                     is the closest real CPU columnar engine available.
  data_gb_per_sec  — bytes of input touched / wall time (MFU-style
                     accounting, shows distance from HBM capability).
  scan_*           — same pipeline including a parquet scan each run.

Tunnel-proofing: the TPU backend rides a tunnel that can flap for hours
(round 4 lost its perf evidence to exactly that).  Before importing jax
in-process we probe the backend in a SUBPROCESS (a failed in-process
backend init is cached by jax and poisons retries) with bounded backoff,
and only emit a structured "backend-unavailable" line after the budget
(env BENCH_BACKEND_WAIT_SECS, default 1800s) is exhausted.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

ROWS = int(os.environ.get("BENCH_ROWS", 1 << 24))
# 16M rows default — large enough that per-dispatch round-trip
# latency (~100ms over the tunneled chip) amortizes.
# ONE batch per chip by default: the reference's steady state is a few
# multi-hundred-MB batches per GPU (2GB target batch size); 16M rows x
# 26B ~= 416MB matches that shape, and every extra partition costs a
# full dispatch round-trip over the tunnel.
PARTS = int(os.environ.get("BENCH_PARTS", "1"))

# BENCH_PLATFORM forces a platform for smoke tests (sitecustomize pins
# JAX_PLATFORMS=axon, so only jax.config.update can override it).
_FORCE = os.environ.get("BENCH_PLATFORM", "")
_PROBE = ("import os, jax; "
          "p = os.environ.get('BENCH_PLATFORM'); "
          "p and jax.config.update('jax_platforms', p); "
          "d = jax.devices(); "
          "import jax.numpy as jnp; "
          "x = jnp.arange(8) + 1; assert int(x.sum()) == 36; "
          "print(d[0].platform)")


def wait_for_backend() -> str:
    """Poll the jax backend in a subprocess until it answers (or the
    budget runs out).  Returns the platform name, or raises TimeoutError
    with the last probe error."""
    budget = float(os.environ.get("BENCH_BACKEND_WAIT_SECS", "1800"))
    deadline = time.monotonic() + budget
    interval, last_err = 30.0, "never probed"
    while True:
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE], capture_output=True,
                text=True, timeout=240)
            if out.returncode == 0 and out.stdout.strip():
                return out.stdout.strip().splitlines()[-1]
            last_err = (out.stderr or "").strip().splitlines()[-1:] or ["?"]
            last_err = last_err[0][-300:]
        except subprocess.TimeoutExpired:
            last_err = "probe timed out after 240s"
        if time.monotonic() >= deadline:
            raise TimeoutError(last_err)
        sys.stderr.write(f"[bench] backend unavailable ({last_err}); "
                         f"retrying in {interval:.0f}s\n")
        time.sleep(min(interval, max(0.0, deadline - time.monotonic())))
        interval = min(interval * 1.5, 120.0)


# Persistent XLA compilation cache: the 16M-row kernels take minutes to
# compile on the tunneled chip; cached executables make warmup near-free
# on every bench invocation after the first.
os.makedirs("/tmp/jax_comp_cache", exist_ok=True)


def _configure_jax():
    import jax
    if _FORCE:
        jax.config.update("jax_platforms", _FORCE)
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_comp_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)


def make_data(rows: int):
    from spark_rapids_tpu import types as T
    rng = np.random.RandomState(42)
    return {
        "ss_item_sk": (T.INT, rng.randint(0, 2000, rows)),
        "ss_promo_sk": (T.INT, rng.randint(0, 3, rows)),
        "ss_quantity": (T.INT, rng.randint(1, 101, rows)),
        "ss_sales_price": (T.DOUBLE, (rng.rand(rows) * 200).round(2)),
        "ss_ext_discount_amt": (T.DOUBLE, (rng.rand(rows) * 100).round(2)),
    }


def build_query(session, data):
    from spark_rapids_tpu import functions as F
    df = session.create_dataframe(data, num_partitions=PARTS)
    # Device-resident input: staged once at warmup (kept spillable).  The
    # reference's hot loops likewise run against GPU-resident batches; and
    # over the axon tunnel host->HBM bandwidth is an environment artifact,
    # not a TPU property.
    df = df.cache()
    # Round 5: the headline grew a second grouping key and min/max aggs —
    # it now exercises the GENERALIZED slot kernel (mixed-radix multi-key
    # packing + scatter min/max), not just the single-key sum/count/avg
    # einsum the round-4 bench was shaped to.
    return (df
            .filter((df["ss_quantity"] < 25) &
                    (df["ss_ext_discount_amt"] > 10.0))
            .with_column("revenue",
                         df["ss_sales_price"] * df["ss_ext_discount_amt"])
            .group_by("ss_item_sk", "ss_promo_sk")
            .agg(F.sum("revenue").alias("sum_rev"),
                 F.count("revenue").alias("cnt"),
                 F.avg("ss_sales_price").alias("avg_price"),
                 F.min("ss_sales_price").alias("min_price"),
                 F.max("revenue").alias("max_rev"))
            .order_by("ss_item_sk", "ss_promo_sk"))


def time_engine(tpu_enabled: bool, data, runs: int = 3,
                econ_detail: bool = True):
    """-> (best wall secs, economics dict).

    The economics dict decomposes where the time goes — the reference
    pays no per-query compile tax (precompiled cudf kernels); here the
    warmup's XLA compile seconds, the steady-state dispatch count, and
    the (metrics-detail-synced) device execution time are all first-class
    numbers instead of folded invisibly into wall time.
    """
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.session import TpuSparkSession
    conf = RapidsConf({
        "spark.rapids.sql.enabled": tpu_enabled,
        "spark.sql.shuffle.partitions": PARTS,
        # Float sum/avg reduce in a data-parallel order on the accelerator;
        # the reference's benchmarks run with the same gate enabled
        # (RapidsConf.scala:400-421 hasNans/variableFloatAgg knobs).
        "spark.rapids.sql.variableFloatAgg.enabled": True,
        # persistent XLA executables: a second bench process pre-warms
        # from disk instead of recompiling the 16M-row kernels
        "spark.rapids.sql.tpu.compileCacheDir": "/tmp/jax_comp_cache",
        # partition deadline armed in bench (off in tier-1): a wedged
        # dispatch over the tunneled chip fails into device-lost
        # recovery instead of eating the whole capture window (the
        # round-5 40-minute single-dot hang shape).  Generous bound —
        # cold 16M-row compiles legitimately take minutes.
        "spark.rapids.sql.tpu.partition.timeoutSec": float(
            os.environ.get("BENCH_PARTITION_TIMEOUT_SECS", "1800")),
    })
    s = TpuSparkSession(conf)
    q = build_query(s, data)
    q.collect()  # warmup (compile)
    warm = dict(s.last_metrics)
    best = float("inf")
    for _ in range(runs):
        t0 = time.monotonic()
        rows = q.collect()
        dt = time.monotonic() - t0
        best = min(best, dt)
    assert rows, "empty result"
    repeat = dict(s.last_metrics)  # steady state: compileCount must be 0
    device = repeat
    if econ_detail:
        # accurate device-time capture: one extra (untimed-for-wall) run
        # with the metrics-detail sync on; the conf key is excluded from
        # the plan cache fingerprint so nothing recompiles
        s.set_conf("spark.rapids.sql.tpu.metrics.detailEnabled", True)
        q.collect()
        device = dict(s.last_metrics)
        s.set_conf("spark.rapids.sql.tpu.metrics.detailEnabled", False)
    obs_overhead_pct = 0.0
    if econ_detail:
        # obs-off timed loop over the same compiled plan (obs confs are
        # excluded from the plan-cache fingerprint, so nothing
        # recompiles): best-on vs best-off wall IS the event bus's cost
        s.set_conf("spark.rapids.sql.tpu.obs.enabled", False)
        best_off = float("inf")
        for _ in range(runs):
            t0 = time.monotonic()
            q.collect()
            best_off = min(best_off, time.monotonic() - t0)
        s.set_conf("spark.rapids.sql.tpu.obs.enabled", True)
        if best_off > 0 and best_off != float("inf"):
            obs_overhead_pct = round(100.0 * (best - best_off) / best_off,
                                     2)
    telemetry_overhead_pct = 0.0
    if econ_detail:
        # telemetry-off timed loop, same compiled plan (obs.* confs are
        # excluded from the plan-cache fingerprint): best-on vs best-off
        # wall IS the continuous aggregation ring's cost
        s.set_conf("spark.rapids.sql.tpu.obs.telemetry.enabled", False)
        best_tel_off = float("inf")
        for _ in range(runs):
            t0 = time.monotonic()
            q.collect()
            best_tel_off = min(best_tel_off, time.monotonic() - t0)
        s.set_conf("spark.rapids.sql.tpu.obs.telemetry.enabled", True)
        if best_tel_off > 0 and best_tel_off != float("inf"):
            telemetry_overhead_pct = round(
                100.0 * (best - best_tel_off) / best_tel_off, 2)
    # critical-path attribution of the newest profiled run: which site
    # dominates the exact wall decomposition (obs.critpath)
    critpath_top_site = ""
    hist = s.query_history()
    if hist:
        from spark_rapids_tpu.obs import critpath as obs_critpath
        cp = obs_critpath.from_profile(hist[-1])
        if cp is not None:
            critpath_top_site = cp.top_site()
    econ = {
        "compile_s": round(warm.get("compileWallNs", 0) / 1e9, 3),
        "compile_count": warm.get("compileCount", 0),
        "recompile_count": repeat.get("compileCount", 0),
        "dispatch_count": repeat.get("dispatchCount", 0),
        "compiled_shapes": repeat.get("compiledShapes", 0),
        "device_ms": round(device.get("deviceTimeNs", 0) / 1e6, 3),
        # data-plane economics: donation is steady-state (every repeat run
        # reuses consumed-input HBM); H2D staging happens at warmup (the
        # cached input stages once), D2H on every collect.  bytes/ns IS
        # GB/s.
        "donated_bytes": repeat.get("donatedBytes", 0),
        "h2d_gb_per_sec": round(
            warm.get("h2dBytes", 0) / warm["h2dTimeNs"], 3)
        if warm.get("h2dTimeNs") else 0.0,
        "d2h_gb_per_sec": round(
            repeat.get("d2hBytes", 0) / repeat["d2hTimeNs"], 3)
        if repeat.get("d2hTimeNs") else 0.0,
        # fault-tolerance economics: nonzero retry/device-lost/fallback
        # counts mean the capture recovered from faults (real or
        # injected via faults.spec) — the throughput number then
        # includes recovery cost, which is exactly the production story
        "retry_count": repeat.get("retryCount", 0),
        "backoff_ms": round(repeat.get("backoffWallNs", 0) / 1e6, 3),
        "device_lost_count": repeat.get("deviceLostCount", 0),
        "partition_fallbacks": repeat.get("partitionFallbackCount", 0),
        "faults_injected": repeat.get("faultsInjected", 0),
        # observability economics: events the steady-state run produced,
        # and the wall-time cost of producing them (obs-on best vs the
        # obs-off loop above; negative values are run-to-run noise)
        "obs_event_count": repeat.get("obsEventCount", 0),
        "obs_overhead_pct": obs_overhead_pct,
        "telemetry_overhead_pct": telemetry_overhead_pct,
        "critpath_top_site": critpath_top_site,
    }
    return best, econ


SCAN_ROWS = min(1 << 22, ROWS)  # 4M-row parquet for the scan metric
# (tracks BENCH_ROWS downward so smoke runs stay small)


def _scan_conf(tpu_enabled: bool):
    from spark_rapids_tpu.config import RapidsConf
    return RapidsConf({
        "spark.rapids.sql.enabled": tpu_enabled,
        "spark.sql.shuffle.partitions": PARTS,
        "spark.rapids.sql.variableFloatAgg.enabled": True,
    })


def time_scan_engine(tpu_enabled: bool, path: str, runs: int = 3) -> float:
    """Same q6-ish pipeline but INCLUDING a file-based parquet scan each
    run (the headline metric starts from device-cached input; this one
    measures the scan path end to end)."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.session import TpuSparkSession
    s = TpuSparkSession(_scan_conf(tpu_enabled))

    def q():
        df = s.read.parquet(path)
        return (df
                .filter((df["ss_quantity"] < 25) &
                        (df["ss_ext_discount_amt"] > 10.0))
                .with_column("revenue", df["ss_sales_price"] *
                             df["ss_ext_discount_amt"])
                .group_by("ss_item_sk")
                .agg(F.sum("revenue").alias("sum_rev"),
                     F.count("revenue").alias("cnt"))
                .collect())

    q()  # warmup (compile)
    best = float("inf")
    for _ in range(runs):
        t0 = time.monotonic()
        rows = q()
        best = min(best, time.monotonic() - t0)
    assert rows, "empty result"
    return best


SCAN_V2_CHUNKS = 16     # row groups in the scan-engine A/B file
SCAN_V2_NEEDLE = 501    # odd tag planted in exactly one chunk (late-mat)


def _scan_v2_conf(v2_enabled: bool):
    from spark_rapids_tpu.config import RapidsConf
    return RapidsConf({
        "spark.rapids.sql.enabled": True,
        "spark.sql.shuffle.partitions": 1,
        "spark.rapids.sql.variableFloatAgg.enabled": True,
        "spark.rapids.sql.tpu.scan.v2.enabled": v2_enabled,
    })


def _scan_v2_dir() -> str:
    """Cached multi-row-group parquet with a dictionary string column and
    a needle tag for the late-materialization probe.  Every chunk's tag
    min/max brackets the needle (so row-group statistics cannot skip —
    the unsorted-column case late materialization exists for) but only
    one chunk actually holds it."""
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq
    n = SCAN_ROWS
    out = os.path.join(tempfile.gettempdir(),
                       f"rapids_tpu_bench_scanv2b_{n}_{SCAN_V2_CHUNKS}")
    part = os.path.join(out, "part-00000.parquet")
    if os.path.exists(part):
        return out
    rng = np.random.RandomState(7)
    cats = np.array([f"cat_{i:04d}" for i in range(256)], dtype=object)
    tag = (rng.randint(-500, 500, n) * 2).astype(np.int64)  # even only
    tag[3 * (n // SCAN_V2_CHUNKS) + 7] = SCAN_V2_NEEDLE     # odd needle
    tb = pa.table({
        "bucket": pa.array(rng.randint(0, 64, n).astype(np.int32)),
        "k": pa.array(rng.randint(0, 1 << 20, n).astype(np.int64)),
        "v": pa.array((rng.rand(n) * 100).round(3)),
        "cat": pa.array(cats[rng.randint(0, 256, n)]),
        "tag": pa.array(tag),
    })
    os.makedirs(out, exist_ok=True)
    pq.write_table(tb, part, row_group_size=max(n // SCAN_V2_CHUNKS, 1))
    return out


def time_scan_v2(runs: int = 3) -> dict:
    """A/B the scan engine itself: same full-table decode + tiny agg with
    scan v2 on vs off (io.scan_v2 vs io.scan on the same host/file).  The
    agg keeps device work negligible so the wall time IS the scan path:
    decode, (dict-)H2D, and one reduction.  A second v2-only query with
    the needle predicate exercises chunk-level late materialization."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.session import TpuSparkSession
    path = _scan_v2_dir()

    def measure(v2_enabled: bool):
        s = TpuSparkSession(_scan_v2_conf(v2_enabled))

        def q():
            # int group key keeps the MXU hash-agg consumer cheap, so the
            # wall measures the scan path; cat stays projected (the dict
            # column the transfer is about) via its count
            df = s.read.parquet(path)
            return df.group_by("bucket").agg(
                F.count("cat").alias("c"), F.sum("v").alias("sv"),
                F.max("k").alias("mk")).collect()

        rows = q()  # warmup (compile)
        assert rows and sum(r[1] for r in rows) == SCAN_ROWS
        best = float("inf")
        for _ in range(runs):
            t0 = time.monotonic()
            q()
            best = min(best, time.monotonic() - t0)
        return best, dict(s.last_metrics)

    v2_t, v2_ms = measure(True)
    v1_t, _v1_ms = measure(False)
    decoded = v2_ms.get("scanBytesDecoded", 0)
    decode_ns = v2_ms.get("scanDecodeWallNs", 0)
    overlap_ns = v2_ms.get("scanH2dOverlapNs", 0)

    # late-mat probe: needle predicate over the unsorted tag column —
    # stats keep every chunk, the exact probe keeps one
    s = TpuSparkSession(_scan_v2_conf(True))
    df = s.read.parquet(path)
    hits = df.filter(df["tag"] == SCAN_V2_NEEDLE).collect()
    assert len(hits) == 1, f"needle rows: {len(hits)}"
    skipped = s.last_metrics.get("scanChunksSkipped", 0)

    return {
        "scan_gb_per_sec": round(decoded / v2_t / 1e9, 3),
        "scan_decode_gb_per_sec": round(decoded / decode_ns, 3)
        if decode_ns > 0 else 0.0,
        "scan_h2d_overlap_pct": round(100.0 * overlap_ns / decode_ns, 1)
        if decode_ns > 0 else 0.0,
        "scan_chunks_skipped": int(skipped),
        "scan_v2_vs_v1": round(v1_t / v2_t, 3),
        # deepest read-ahead depth the adaptive controller actually used
        # (== scan.readAhead.depth when adaptive is off or never raised)
        "readahead_depth_effective": int(
            v2_ms.get("readaheadDepthEffective", 0)),
    }


def time_pandas(data, runs: int = 5) -> float:
    """Same q6 pipeline in pandas (C-backed columnar CPU engine) — the
    engine-independent baseline.  pyspark is not installable here (zero
    egress); pandas groupby is the nearest real CPU columnar reference.

    MEDIAN of ``runs`` (not best-of): the baseline is a denominator, and a
    lucky best-of-3 on a noisy host swung vs_pandas_cpu 2.4x between
    round-5 captures.  The median is additionally PINNED to a per-(rows,
    schema) cache file so later captures on the same machine divide by the
    same number (env BENCH_REPIN=1 forces a fresh measurement).
    """
    import statistics

    import pandas as pd
    pin_path = _baseline_pin_path(data)
    if pin_path and os.path.exists(pin_path) and \
            not os.environ.get("BENCH_REPIN"):
        try:
            with open(pin_path) as f:
                return float(json.load(f)["pandas_cpu_s"])
        except (ValueError, KeyError, OSError):
            pass
    df = pd.DataFrame({k: v for k, (_, v) in data.items()})
    times = []
    for _ in range(runs):
        t0 = time.monotonic()
        f = df[(df["ss_quantity"] < 25) & (df["ss_ext_discount_amt"] > 10.0)]
        f = f.assign(revenue=f["ss_sales_price"] * f["ss_ext_discount_amt"])
        out = (f.groupby(["ss_item_sk", "ss_promo_sk"])
                .agg(sum_rev=("revenue", "sum"),
                     cnt=("revenue", "count"),
                     avg_price=("ss_sales_price", "mean"),
                     min_price=("ss_sales_price", "min"),
                     max_rev=("revenue", "max"))
                .sort_index())
        times.append(time.monotonic() - t0)
    assert len(out), "empty pandas result"
    med = statistics.median(times)
    if pin_path:
        try:
            with open(pin_path, "w") as f:
                json.dump({"pandas_cpu_s": med, "runs": runs}, f)
        except OSError:
            pass
    return med


def _baseline_pin_path(data):
    import hashlib
    import tempfile
    sig = hashlib.sha1(repr([(k, str(t), np.asarray(v).dtype.str)
                             for k, (t, v) in data.items()])
                       .encode()).hexdigest()[:8]
    return os.path.join(tempfile.gettempdir(),
                        f"rapids_tpu_bench_baseline_{ROWS}_{sig}.json")


def _bytes_per_row(data) -> int:
    return sum(int(np.asarray(v).dtype.itemsize) for _, v in data.values())


def time_shuffle():
    """Single-host shuffle split microbench: a non-collapsed round-robin
    exchange (B=4 input partitions -> N=8 targets), reporting the split
    engine's economics — throughput from the split's own byte/wall
    accounting plus the dispatch/sync counts the v2 coalescing engine
    minimizes (~B+N dispatches, exactly 1 host sync per exchange)."""
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.session import TpuSparkSession
    rows = min(ROWS, 1 << 20)
    s = TpuSparkSession(RapidsConf({
        "spark.rapids.sql.enabled": True,
        "spark.sql.shuffle.partitions": 8,
        "spark.rapids.sql.tpu.exchange.collapseLocal": False,
        "spark.rapids.sql.variableFloatAgg.enabled": True,
    }))
    df = s.create_dataframe(make_data(rows), num_partitions=4)
    q = df.repartition(8)
    q.collect()  # warmup (compile)
    q.collect()
    m = s.last_metrics
    wall = m.get("shuffleWallNs", 0)
    gbps = round(m.get("shuffleBytes", 0) / wall, 3) if wall else 0.0
    return gbps, m.get("shuffleSplitDispatches", 0), m.get("shuffleSyncs", 0)


def time_string_shuffle():
    """Dict-aware shuffle lane: a non-collapsed round-robin exchange over
    a scanned table whose string column arrives dictionary-encoded (the
    v2 scan keeps codes on device; exchange.dictAware moves 4-byte codes
    plus one dictionary per piece instead of materialized string bytes).
    shuffle_encoded_bytes_saved is the wire-byte reduction vs the
    materialized layout; wire throughput divides the bytes actually
    moved by the split wall."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.session import TpuSparkSession
    path = _scan_v2_dir()
    s = TpuSparkSession(RapidsConf({
        "spark.rapids.sql.enabled": True,
        "spark.sql.shuffle.partitions": 8,
        "spark.rapids.sql.tpu.exchange.collapseLocal": False,
        "spark.rapids.sql.variableFloatAgg.enabled": True,
        "spark.rapids.sql.tpu.scan.v2.enabled": True,
    }))

    def q():
        # repartition forces a real exchange of the whole table (cat
        # rides encoded); the tiny agg keeps the collect cheap so the
        # wall is the shuffle, not row materialization
        df = s.read.parquet(path).repartition(8)
        return df.group_by("bucket").agg(F.count("cat").alias("c"),
                                         F.sum("v").alias("sv")).collect()

    rows = q()  # warmup (compile)
    assert rows and sum(r[1] for r in rows) == SCAN_ROWS
    q()
    m = s.last_metrics
    saved = m.get("shuffleEncodedBytesSaved", 0)
    wall = m.get("shuffleWallNs", 0)
    wire = max(m.get("shuffleBytes", 0) - saved, 0)
    gbps = round(wire / wall, 3) if wall else 0.0
    return gbps, int(saved)


def time_adaptive():
    """Adaptive replanning microbench (plan/adaptive): a one-hot-key
    shuffled join (coalescing + skew split) and an aggregate-input join
    (runtime shuffled->broadcast switch), each run with adaptive on and
    off on identical data.  Returns (rows/s adaptive-on, on/off speedup,
    rows bit-identical on vs off, aqe counter dict)."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.session import TpuSparkSession
    from spark_rapids_tpu import types as T
    rows = min(ROWS, 1 << 18)
    rng = np.random.RandomState(7)
    hot = np.where(rng.rand(rows) < 0.9, 0,
                   rng.randint(1, 64, rows)).astype(np.int32)
    fact = {
        "k": (T.INT, hot.tolist()),
        "v": (T.LONG, list(range(rows))),
    }
    dim = {
        "k": (T.INT, list(range(64))),
        "w": (T.LONG, [i * 10 for i in range(64)]),
    }

    def run(adaptive_on):
        s = TpuSparkSession(RapidsConf({
            "spark.rapids.sql.enabled": True,
            "spark.rapids.sql.tpu.exchange.collapseLocal": False,
            "spark.sql.shuffle.partitions": 8,
            "spark.sql.autoBroadcastJoinThreshold": -1,
            "spark.rapids.sql.tpu.adaptive.enabled": adaptive_on,
            "spark.rapids.sql.tpu.adaptive.coalesce.targetBytes": 1 << 20,
            "spark.rapids.sql.tpu.adaptive.skew.thresholdBytes": 1 << 16,
        }))
        big = s.create_dataframe(fact, num_partitions=4)
        small = s.create_dataframe(dim, num_partitions=2)
        q = big.join(small, on="k", how="inner")
        q.collect()  # warmup (compile)
        t0 = time.monotonic()
        out = q.collect()
        wall = time.monotonic() - t0
        counters = {k: s.last_metrics.get(k, 0) for k in (
            "aqeCoalescedPartitions", "aqeSkewSplits",
            "aqeEstimateErrorPct")}
        # the switch needs a replan-eligible shape: aggregate inputs
        # (plan-time size unknown) and a live broadcast threshold
        s.set_conf("spark.sql.autoBroadcastJoinThreshold", 10 << 20)
        bq = big.group_by("k").agg(F.sum("v").alias("sv")).join(
            small.group_by("k").agg(F.sum("w").alias("sw")), on="k")
        bq.collect()
        counters["aqeBroadcastSwitches"] = \
            s.last_metrics.get("aqeBroadcastSwitches", 0)
        return wall, sorted(out), counters

    on_wall, on_rows, counters = run(True)
    off_wall, off_rows, _off = run(False)
    speedup = round(off_wall / on_wall, 3) if on_wall else 0.0
    return (round(len(on_rows) / on_wall, 1) if on_wall else 0.0,
            speedup, on_rows == off_rows, counters)


def time_history():
    """Query-intelligence lane (history/): warm-vs-cold wall on the same
    aggregation with a fresh statistics store.  Both timed runs are
    compile-free (the plan's programs are warmed first); the cold run
    re-executes the whole subtree, the warm run serves it from the
    cross-query fragment cache — the ratio is pure fragment-reuse
    speedup.  Returns (warm speedup, fragmentCacheHits of the warm run,
    regressionAlerts of the warm run — the sentinel must stay silent on
    a run that got FASTER)."""
    import shutil
    import tempfile

    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.history.fragcache import fragment_cache
    from spark_rapids_tpu.session import TpuSparkSession
    rows = min(ROWS, 1 << 18)
    hist_dir = tempfile.mkdtemp(prefix="rapids_tpu_bench_hist_")
    try:
        s = TpuSparkSession(RapidsConf({
            "spark.rapids.sql.enabled": True,
            # float sums stay on-device (tpcds suite convention) — the
            # CPU-fallback plan would bypass the fragment cache entirely
            "spark.rapids.sql.variableFloatAgg.enabled": True,
            "spark.rapids.sql.tpu.history.dir": hist_dir,
        }))
        df = s.create_dataframe(make_data(rows), num_partitions=4)
        q = df.group_by("ss_promo_sk").agg(
            F.sum("ss_sales_price").alias("sum_price"),
            F.count("ss_quantity").alias("cnt"))
        q.collect()  # warmup: compile + first store record
        fragment_cache().clear()
        t0 = time.monotonic()
        cold = q.collect()  # full re-execution (compile-free)
        cold_wall = time.monotonic() - t0
        t0 = time.monotonic()
        warm = q.collect()  # fragment-cache hit
        warm_wall = time.monotonic() - t0
        hits = s.last_metrics.get("fragmentCacheHits", 0)
        alerts = s.last_metrics.get("regressionAlerts", 0)
        assert sorted(cold) == sorted(warm), "history warm/cold parity"
        speedup = round(cold_wall / warm_wall, 3) if warm_wall else 0.0
        return speedup, hits, alerts
    finally:
        shutil.rmtree(hist_dir, ignore_errors=True)


def _async_partitions_default() -> bool:
    from spark_rapids_tpu.config import PIPELINE_ASYNC_PARTITIONS, RapidsConf
    return bool(PIPELINE_ASYNC_PARTITIONS.get(RapidsConf()))


def time_serve():
    """Serving runtime lane (serve/): the weighted two-tenant template
    workload from serve.bench — steady-state queries/sec through the
    scheduler, coalesced-dispatch counts, serial-vs-served wall ratio,
    bit-parity, and the shared executable cache's second-session
    compile count (must be 0)."""
    from spark_rapids_tpu.serve.bench import run_serve_bench
    return run_serve_bench(queries=32, rows=512,
                           tenants={"a": 2.0, "b": 1.0},
                           max_concurrency=2)


def time_frontend():
    """Network front-door lane (serve/frontend): the demo SQL workload
    through a real TCP socket — queries/sec and client-observed
    p50/p99 over concurrent per-tenant connections, socket-vs-serial
    wall ratio, bit-parity against in-process execution, the second
    client connection's compile count (must be 0), warm-repeat result
    cache hits (zero compiles AND zero dispatches) and the admission
    controller's sentinel-predicted deadline shed."""
    from spark_rapids_tpu.serve.bench import run_frontend_bench
    return run_frontend_bench(queries=24, rows=2048,
                              tenants={"a": 2.0, "b": 1.0},
                              max_concurrency=2)


def time_spill():
    """Spill engine microbench: pre-stage device batches (untimed), then
    register them against a budget that forces most to spill to host and
    drain — timed.  Registers are cheap; the wall is the D2H spill copies,
    so bytes-spilled / wall is the engine's spill throughput.  Run twice,
    async writer vs v1 synchronous, on identical inputs: the async win is
    the writer pool overlapping copies that v1 serialized inside the
    budget loop."""
    from spark_rapids_tpu.batch import HostBatch, host_to_device
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.mem.catalog import BufferCatalog

    from spark_rapids_tpu import types as T
    n_batches = 8
    rows = max(1, min(ROWS, 1 << 22) // n_batches)
    hosts = [HostBatch.from_pydict({
        "a": (T.LONG, (np.arange(rows, dtype=np.int64) + i).tolist()),
        "b": (T.DOUBLE, np.full(rows, float(i)).tolist()),
    }) for i in range(n_batches)]

    def one(async_enabled):
        devices = [host_to_device(hb) for hb in hosts]
        for d in devices:
            for c in d.columns:
                c.data.block_until_ready()
        cat = BufferCatalog(RapidsConf({
            # every register past the first must evict its predecessor
            "spark.rapids.memory.tpu.spillBudgetBytes": 1,
            "spark.rapids.memory.host.spillStorageSize": 1 << 40,
            "spark.rapids.sql.tpu.spill.async.enabled": async_enabled,
        }))
        t0 = time.perf_counter()
        handles = [cat.register(d) for d in devices]
        cat.drain_spills()
        wall = time.perf_counter() - t0
        spilled = cat.metrics["spill_to_host_bytes"]
        depth = cat.metrics["spill_queue_depth_max"]
        for h in handles:
            h.close()
        gbps = round(spilled / wall / 1e9, 3) if wall > 0 else 0.0
        return gbps, depth

    async_gbps, depth = one(True)
    sync_gbps, _ = one(False)
    speedup = round(async_gbps / sync_gbps, 3) if sync_gbps else 0.0
    return async_gbps, sync_gbps, speedup, depth


_MESH_CHILD = r"""
import json, os, sys, time
import numpy as np
n = int(sys.argv[1]); spmd = sys.argv[2] == "on"; rows = int(sys.argv[3])
from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.session import TpuSparkSession
rng = np.random.RandomState(11)
s = TpuSparkSession(RapidsConf({
    "spark.rapids.sql.enabled": True,
    "spark.rapids.shuffle.ici.enabled": True,
    "spark.rapids.sql.variableFloatAgg.enabled": True,
    "spark.rapids.sql.tpu.mesh.spmd.enabled": spmd,
    "spark.sql.shuffle.partitions": max(2, n),
    "spark.sql.autoBroadcastJoinThreshold": 0,
}))
df = s.create_dataframe({
    "k": (T.INT, rng.randint(0, 64, rows).astype(np.int32).tolist()),
    "v": (T.LONG, list(range(rows))),
}, num_partitions=max(2, n))
q = df.group_by("k").agg(F.sum("v").alias("sv"))
q.collect()  # warmup (compile)
t0 = time.monotonic()
q.collect()
wall = time.monotonic() - t0
m = s.last_metrics
# join-bearing query: a shuffled hash join ACROSS the exchange, fused
# into the same shard_map program when SPMD is on (threshold 0 above
# keeps the hash strategy)
right = s.create_dataframe({
    "k": (T.INT, list(range(64))),
    "w": (T.LONG, [i * 3 for i in range(64)]),
}, num_partitions=2)
jq = df.join(right, on="k", how="inner").group_by("k").agg(
    F.sum(F.col("w")).alias("sw"))
jq.collect()  # warmup (compile)
t0 = time.monotonic()
jq.collect()
jwall = time.monotonic() - t0
jm = s.last_metrics
print(json.dumps({
    "rows_per_sec": round(rows / wall, 1) if wall > 0 else 0.0,
    "backend": m.get("meshBackend", ""),
    "fused": m.get("meshBoundariesFused", 0),
    "join_rows_per_sec": round(rows / jwall, 1) if jwall > 0 else 0.0,
    "join_fused": jm.get("meshJoinsFused", 0),
    "fallbacks": jm.get("meshFallbacks", 0),
}))
"""


def time_mesh():
    """Multichip mesh-SPMD lane: the same two-stage shuffle query
    (partial agg -> hash exchange -> merge agg) timed in subprocess
    children pinned to 1/2/4/8 CPU virtual devices
    (``--xla_force_host_platform_device_count``), SPMD fusion on — the
    scaling curve — plus one SPMD-off child at the widest mesh for the
    fused-vs-host-driven ratio.  Children force JAX_PLATFORMS=cpu so the
    curve is honest about its backend: ``mesh_backend`` records what the
    shuffle mesh actually ran on, and the ratio is informational on CPU
    (host collectives emulate ICI; it is NOT gated)."""
    rows = min(ROWS, 1 << 14)

    def child(n, spmd):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n}"
        try:
            out = subprocess.run(
                [sys.executable, "-c", _MESH_CHILD, str(n),
                 "on" if spmd else "off", str(rows)],
                capture_output=True, text=True, timeout=300, env=env)
            line = out.stdout.strip().splitlines()[-1]
            return json.loads(line)
        except (subprocess.TimeoutExpired, IndexError,
                json.JSONDecodeError):
            return {"rows_per_sec": 0.0, "backend": "", "fused": 0,
                    "join_rows_per_sec": 0.0, "join_fused": 0,
                    "fallbacks": 0}

    curve = {}
    join_curve = {}
    backend = ""
    join_fused = 0
    fallbacks = 0
    for n in (1, 2, 4, 8):
        r = child(n, True)
        curve[str(n)] = r["rows_per_sec"]
        join_curve[str(n)] = r.get("join_rows_per_sec", 0.0)
        join_fused = max(join_fused, r.get("join_fused", 0))
        fallbacks += r.get("fallbacks", 0)
        if r["backend"]:
            backend = r["backend"]
    off = child(8, False)
    on_rps = curve.get("8", 0.0)
    ratio = round(on_rps / off["rows_per_sec"], 3) \
        if off["rows_per_sec"] else 0.0
    return curve, ratio, backend, join_curve, join_fused, fallbacks


def time_pallas():
    """Pallas kernel-tier lane (kernels.pallas_tier): the conf-enabled
    kernel list, each kernel's interpret-mode wall vs its XLA fallback on
    identical micro inputs (informational on CPU — interpret mode
    emulates the kernel program, so the ratio measures the emulation
    cost, not the TPU win; the chip run reports the real speedups), and
    the fallback count a default-conf run pays on this backend (every
    engaged kernel falls back off-TPU; 0 on a real TPU).  Folds in the
    old benchmarks/pallas_strings_bench.py contains-scan shape."""
    import jax

    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.batch import HostBatch, host_to_device
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exprs import strings as S
    from spark_rapids_tpu.exprs.base import DevVal
    from spark_rapids_tpu.kernels import layout as KL
    from spark_rapids_tpu.kernels import pallas_tier as PT
    from spark_rapids_tpu.kernels.join import join_pairs_static

    enabled = [spec.name for spec in PT.registered()
               if bool(spec.entry.get(RapidsConf()))]

    rng = np.random.RandomState(3)
    n = 512
    alphabet = list("abnexzle")
    strs = ["".join(rng.choice(alphabet, rng.randint(0, 16)))
            for _ in range(n)]
    batch = host_to_device(HostBatch.from_pydict({
        "k": (T.INT, rng.randint(0, 64, n).astype(np.int32).tolist()),
        "s": (T.STRING, strs),
    }))
    kcol, scol = batch.columns
    kval = DevVal(kcol.dtype, kcol.data, kcol.validity, kcol.offsets)
    sval = DevVal(scol.dtype, scol.data, scol.validity, scol.offsets)

    workloads = {
        "strings": lambda: S._rows_with_match(sval, b"ab"),
        "stringHash": lambda: S.string_hash2(sval),
        "gatherScatter": lambda: KL.concat_kway(
            [batch, batch], 2 * batch.capacity),
        "joinProbe": lambda: join_pairs_static(
            [kval], batch.num_rows, [kval], batch.num_rows, 8192),
    }
    all_off = {spec.entry.key: False for spec in PT.registered()}

    def wall(fn, conf):
        PT.configure(conf)
        try:
            jax.block_until_ready(fn())  # warm (compile/trace)
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            return time.perf_counter() - t0
        finally:
            PT.configure(None)

    speedup = {}
    for name, fn in workloads.items():
        on = dict(all_off)
        on[PT._KERNELS[name].entry.key] = True
        on["spark.rapids.sql.tpu.pallas.interpret"] = True
        xla_s = wall(fn, RapidsConf(all_off))
        pal_s = wall(fn, RapidsConf(on))
        speedup[name] = round(xla_s / pal_s, 3) if pal_s > 0 else 0.0

    # fallback economics: default confs (kernels on, interpret off) on
    # THIS backend — each engaged kernel decision off-TPU is one fallback
    PT.configure(RapidsConf())
    try:
        fb0 = PT.fallback_count()
        jax.block_until_ready(S._rows_with_match(sval, b"zq"))
        jax.block_until_ready(S.string_hash2(sval))
        fallbacks = PT.fallback_count() - fb0
    finally:
        PT.configure(None)
    return enabled, speedup, fallbacks


def main():
    try:
        platform = wait_for_backend()
    except TimeoutError as e:
        print(json.dumps({
            "metric": "q6_like_rows_per_sec", "value": 0.0, "unit": "rows/s",
            "vs_baseline": 0.0, "error": "backend-unavailable",
            "detail": str(e),
            "wait_budget_secs": float(
                os.environ.get("BENCH_BACKEND_WAIT_SECS", "1800")),
        }))
        return
    sys.stderr.write(f"[bench] backend up: platform={platform}\n")
    _configure_jax()
    data = make_data(ROWS)
    tpu_t, tpu_econ = time_engine(True, data)
    # the CPU engine's econ dict is unused — skip its extra detail run
    cpu_t, _cpu_econ = time_engine(False, data, econ_detail=False)
    pandas_t = time_pandas(data)
    value = ROWS / tpu_t
    vs = cpu_t / tpu_t

    # scan-inclusive secondary metric (same JSON line: the driver parses
    # one line; extra keys carry the second benchmark)
    import hashlib
    import tempfile
    # row count + schema fingerprint in the dir name: a SCAN_ROWS or
    # make_data schema change can never silently reuse a stale file
    sig = hashlib.sha1(repr([(k, str(t), np.asarray(v).dtype.str)
                             for k, (t, v) in data.items()])
                       .encode()).hexdigest()[:8]
    scan_dir = os.path.join(tempfile.gettempdir(),
                            f"rapids_tpu_bench_pq_{SCAN_ROWS}_{sig}")
    scan_file = os.path.join(scan_dir, "part-00000.parquet")
    if not os.path.exists(scan_file):
        from spark_rapids_tpu.session import TpuSparkSession
        s = TpuSparkSession(_scan_conf(False))
        df = s.create_dataframe(make_data(SCAN_ROWS), num_partitions=1)
        df.write_parquet(scan_dir, mode="overwrite")
    scan_tpu = time_scan_engine(True, scan_dir)
    scan_cpu = time_scan_engine(False, scan_dir)
    scan_v2 = time_scan_v2()
    shuffle_gbps, shuffle_dispatches, shuffle_syncs = time_shuffle()
    shuffle_wire_gbps, shuffle_saved = time_string_shuffle()
    spill_gbps, spill_sync_gbps, spill_speedup, spill_depth = time_spill()
    aqe_rps, aqe_speedup, aqe_parity, aqe_counters = time_adaptive()
    serve = time_serve()
    frontend = time_frontend()
    history_speedup, history_hits, history_alerts = time_history()
    (mesh_curve, mesh_ratio, mesh_backend, mesh_join_curve,
     mesh_join_fused, mesh_fallbacks) = time_mesh()
    pallas_enabled, pallas_speedup, pallas_fallbacks = time_pallas()

    data_bytes = ROWS * _bytes_per_row(data)
    device_s = tpu_econ["device_ms"] / 1e3
    print(json.dumps({
        "metric": "q6_like_rows_per_sec",
        "value": round(value, 1),
        "unit": "rows/s",
        "vs_baseline": round(vs, 3),
        "vs_pandas_cpu": round(pandas_t / tpu_t, 3),
        "pandas_cpu_s": round(pandas_t, 4),
        "data_gb_per_sec": round(data_bytes / tpu_t / 1e9, 3),
        # compile/dispatch economics (session.last_metrics deltas): wall
        # time now decomposes into compile (warmup-only), device execution
        # (block_until_ready-synced) and the dispatch count the fused-tail
        # pipeline minimizes
        "compile_s": tpu_econ["compile_s"],
        "compile_count": tpu_econ["compile_count"],
        "recompile_count": tpu_econ["recompile_count"],
        "dispatch_count": tpu_econ["dispatch_count"],
        "compiled_shapes": tpu_econ["compiled_shapes"],
        "device_ms": tpu_econ["device_ms"],
        "device_gb_per_sec": round(data_bytes / device_s / 1e9, 3)
        if device_s > 0 else 0.0,
        # data-plane economics: steady-state donated input bytes, the
        # host->device staging rate (warmup: the cached input stages once)
        # and the device->host result-copy rate (every collect)
        "donated_bytes": tpu_econ["donated_bytes"],
        "h2d_gb_per_sec": tpu_econ["h2d_gb_per_sec"],
        "d2h_gb_per_sec": tpu_econ["d2h_gb_per_sec"],
        # shuffle split engine economics (non-collapsed exchange
        # microbench): split throughput plus the dispatch/sync counts the
        # one-sync coalescing split minimizes
        "shuffle_gb_per_sec": shuffle_gbps,
        "shuffle_split_dispatches": shuffle_dispatches,
        "shuffle_syncs": shuffle_syncs,
        # dict-aware shuffle lane (string-heavy exchange): bytes that
        # actually crossed the wire per second once encoded columns move
        # as codes+dictionary, and the wire bytes saved vs materializing
        "shuffle_wire_gb_per_sec": shuffle_wire_gbps,
        "shuffle_encoded_bytes_saved": shuffle_saved,
        "async_partitions": _async_partitions_default(),
        # spill engine v2 economics (catalog microbench): async-writer
        # spill throughput, the v1 synchronous throughput on the same
        # batches, their ratio, and the deepest the writer queue got
        "spill_gb_per_sec": spill_gbps,
        "spill_sync_gb_per_sec": spill_sync_gbps,
        "spill_async_speedup": spill_speedup,
        "spill_queue_depth_max": spill_depth,
        # adaptive execution economics (plan/adaptive microbench): replan
        # counters from a skewed join + a runtime broadcast switch, the
        # adaptive-on/off wall ratio, and whether the two plans returned
        # bit-identical rows
        "aqe_rows_per_sec": aqe_rps,
        "aqe_speedup": aqe_speedup,
        "aqe_parity": aqe_parity,
        "aqe_coalesced_partitions": aqe_counters["aqeCoalescedPartitions"],
        "aqe_broadcast_switches": aqe_counters["aqeBroadcastSwitches"],
        "aqe_skew_splits": aqe_counters["aqeSkewSplits"],
        "aqe_estimate_error_pct": round(
            aqe_counters["aqeEstimateErrorPct"], 3),
        # fault-tolerance counters for the steady-state run (fault/)
        "retry_count": tpu_econ["retry_count"],
        "device_lost_count": tpu_econ["device_lost_count"],
        "partition_fallbacks": tpu_econ["partition_fallbacks"],
        "faults_injected": tpu_econ["faults_injected"],
        # observability economics (obs/): steady-state event volume and
        # the measured wall cost of the always-on event bus
        "obs_event_count": tpu_econ["obs_event_count"],
        "obs_overhead_pct": tpu_econ["obs_overhead_pct"],
        # obs v2 economics: the continuous telemetry ring's measured wall
        # cost (same A/B discipline as obs_overhead_pct), the site the
        # exact critical-path decomposition blames for the steady-state
        # run, and the regression sentinel's alert count on the history
        # lane's warm run (must be 0 — getting faster is not a
        # regression)
        "telemetry_overhead_pct": tpu_econ["telemetry_overhead_pct"],
        "critpath_top_site": tpu_econ["critpath_top_site"],
        "regression_alerts": history_alerts,
        # serving runtime economics (serve/): steady-state scheduler
        # throughput/latency on the weighted two-tenant template
        # workload, the coalesced-query count, served-vs-serial wall
        # ratio (bit-parity checked), the shared executable cache's
        # second-session compile count (0 = every compile amortized
        # process-wide) and the per-tenant SLO rollups
        "serve_queries_per_sec": serve["serve_queries_per_sec"],
        "serve_p50_ms": serve["serve_p50_ms"],
        "serve_p99_ms": serve["serve_p99_ms"],
        "serve_batched_queries": serve["serve_batched_queries"],
        "serve_vs_serial": serve["serve_vs_serial"],
        "serve_parity": serve["serve_parity"],
        "serve_second_session_compiles":
            serve["serve_second_session_compiles"],
        "serve_tenants": serve["serve_tenants"],
        # network front-door lane (serve/frontend): the same serving
        # guarantees over a real TCP socket — out-of-process clients'
        # queries/sec and observed latency, socket-vs-serial ratio,
        # bit-parity vs in-process rows, the second client connection's
        # compile count (0 = the shared plan cache spans connections),
        # warm-repeat result cache hits (each answered with zero
        # compiles and zero dispatches) and sentinel-driven admission
        # sheds (a predicted deadline miss failed fast, pre-execution)
        "frontend_queries_per_sec": frontend["frontend_queries_per_sec"],
        "frontend_p50_ms": frontend["frontend_p50_ms"],
        "frontend_p99_ms": frontend["frontend_p99_ms"],
        "frontend_vs_serial": frontend["frontend_vs_serial"],
        "frontend_parity": frontend["frontend_parity"],
        "frontend_second_client_compiles":
            frontend["frontend_second_client_compiles"],
        "result_cache_hits": frontend["result_cache_hits"],
        "admission_shed": frontend["admission_shed"],
        # query-intelligence lane (history/): warm-vs-cold wall ratio on
        # the same aggregation (both runs compile-free — the warm run
        # serves the whole subtree from the cross-query fragment cache
        # with zero dispatches) and the warm run's hit count
        "history_warm_speedup": history_speedup,
        "fragment_cache_hits": history_hits,
        # mesh-SPMD lane (parallel.mesh_spmd): rows/s scaling curve over
        # 1/2/4/8 virtual devices with whole-stage fusion on, the
        # fused-vs-host-driven throughput ratio at the widest mesh
        # (informational — NOT gated on CPU, where host collectives
        # emulate ICI), and the backend the mesh actually ran on
        "mesh_rows_per_sec_by_devices": mesh_curve,
        "mesh_spmd_vs_hostdriven": mesh_ratio,
        "mesh_backend": mesh_backend,
        # mesh-SPMD v2 fused-join lane: a shuffled hash join compiled
        # INTO the fused program — fused-join count at the widest mesh
        # (>=1 = the join actually fused), the join query's rows/s
        # scaling curve, and the overflow/compat fallback count across
        # all SPMD-on children (0 = default growth never overflowed)
        "mesh_join_fused": mesh_join_fused,
        "mesh_join_rows_per_sec_by_devices": mesh_join_curve,
        "mesh_fallback_count": mesh_fallbacks,
        # pallas kernel-tier lane (kernels.pallas_tier): which kernels
        # the default confs enable, per-kernel XLA-vs-pallas wall ratio
        # (interpret-mode emulation on CPU — informational; the chip run
        # reports the real win), and the fallback count default confs
        # pay on this backend (0 on a real TPU)
        "pallas_kernels_enabled": pallas_enabled,
        "pallas_speedup_by_kernel": pallas_speedup,
        "pallas_fallback_count": pallas_fallbacks,
        "platform": platform,
        "scan_rows_per_sec": round(SCAN_ROWS / scan_tpu, 1),
        "scan_vs_baseline": round(scan_cpu / scan_tpu, 3),
        # scan-engine economics (io.scan_v2 A/B on the same host/file):
        # end-to-end decode rate, pool-side decode rate, the share of
        # decode wall hidden behind the consumer, late-mat chunks skipped
        # on the needle probe, and the v2/v1 wall ratio
        "scan_gb_per_sec": scan_v2["scan_gb_per_sec"],
        "scan_decode_gb_per_sec": scan_v2["scan_decode_gb_per_sec"],
        "scan_h2d_overlap_pct": scan_v2["scan_h2d_overlap_pct"],
        "scan_chunks_skipped": scan_v2["scan_chunks_skipped"],
        "scan_v2_vs_v1": scan_v2["scan_v2_vs_v1"],
        "readahead_depth_effective": scan_v2["readahead_depth_effective"],
    }))


if __name__ == "__main__":
    main()
